"""GraphOptimizer pass suite (autodiff/passes.py): per-pass rewrite
unit tests on hand-built graphs, the full-pipeline fixpoint, and the
end-to-end exactness proofs on a real imported TF BERT and a
hand-encoded ONNX transformer (r5 methodology: identical loss and
identical 4-step training trajectory, optimize-on vs optimize-off)."""
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.passes import (
    _REWRITES, GraphOptimizer, attention_fuse, cast_fold, gelu_refuse,
    graphopt_enabled, layernorm_refuse, mask_strength_reduce, optimize)
from deeplearning4j_tpu.autodiff.samediff import SameDiff

R = np.random.RandomState(0)


def _ops(sd, name):
    return [o for o in sd.ops if o.op_name == name]


# ---------------------------------------------------------------- cast_fold
class TestCastFold:
    def test_identity_cast_repoints_consumers(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(3, 4))
        c = sd._op("cast", [x], {"dtype": "float32"})
        sd._op("mul", [c, c]).rename("y")
        feeds = {"x": R.randn(3, 4).astype(np.float32)}
        want = sd.output(feeds, ["y"])["y"]
        assert cast_fold(sd) == 1
        mul = _ops(sd, "mul")[0]
        assert mul.inputs == ["x", "x"]
        np.testing.assert_array_equal(
            np.asarray(sd.output(feeds, ["y"])["y"]), np.asarray(want))

    def test_roundtrip_collapses_to_direct_read(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(4,))
        up = sd._op("cast", [x], {"dtype": "float64"})
        dn = sd._op("cast", [up], {"dtype": "float32"})
        sd._op("add", [dn, dn]).rename("y")
        feeds = {"x": R.randn(4).astype(np.float32)}
        want = sd.output(feeds, ["y"])["y"]
        counts = optimize(sd, passes=[("cast_fold", cast_fold)])
        # hop 1: outer cast reads x directly; hop 2: it becomes an
        # identity cast and the add reads x — two rewrites at fixpoint
        assert counts["cast_fold"] == 2
        assert _ops(sd, "add")[0].inputs == ["x", "x"]
        np.testing.assert_array_equal(
            np.asarray(sd.output(feeds, ["y"])["y"]), np.asarray(want))

    def test_constant_cast_folds_at_import_time(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(4,))
        tbl = sd.constant("tbl", np.arange(4, dtype=np.int32))
        c = sd._op("cast", [tbl], {"dtype": "float32"})
        sd._op("mul", [x, c]).rename("y")
        assert cast_fold(sd) == 1
        new = _ops(sd, "mul")[0].inputs[1]
        assert new != c.name and "tbl__as_float32" in new
        assert sd._arrays[new].dtype == np.float32
        feeds = {"x": R.randn(4).astype(np.float32)}
        np.testing.assert_array_equal(
            np.asarray(sd.output(feeds, ["y"])["y"]),
            feeds["x"] * np.arange(4, dtype=np.float32))

    def test_idempotent(self):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(4,))
        c = sd._op("cast", [x], {"dtype": "float32"})
        sd._op("mul", [c, c]).rename("y")
        assert cast_fold(sd) == 1
        assert cast_fold(sd) == 0


# ------------------------------------------------- mask_strength_reduce
def _mask_graph(neg=-1e9, mask_dtype=None, extra_add_consumer=False):
    import jax.numpy as jnp
    sd = SameDiff.create()
    s = sd.placeholder("s", shape=(2, 2, 4, 6))
    m = sd.placeholder("m", shape=(2, 6),
                       dtype=mask_dtype or jnp.int32)
    mf = sd._op("cast", [m], {"dtype": "float32"})
    sub = sd._op("sub", [sd.constant("one", np.float32(1.0)), mf])
    mul = sd._op("mul", [sub, sd.constant("neg", np.float32(neg))])
    b = sd._op("expand_dims", [mul], {"axis": 1})
    b = sd._op("expand_dims", [b], {"axis": 2})
    a = sd._op("add", [s, b])
    if extra_add_consumer:
        sd._op("reduce_sum", [a], {"axis": None}).rename("side")
    sd.nn.softmax(a).rename("p")
    return sd


_MASK_FEEDS = {
    "s": R.randn(2, 2, 4, 6).astype(np.float32),
    "m": np.asarray([[1, 1, 1, 1, 0, 0], [1, 1, 1, 1, 1, 1]],
                    np.int32)}


class TestMaskStrengthReduce:
    def test_rewrites_to_key_mask_bitwise_exact(self):
        sd = _mask_graph()
        want = sd.output(_MASK_FEEDS, ["p"])["p"]
        assert mask_strength_reduce(sd) == 1
        akm = _ops(sd, "apply_key_mask")
        assert len(akm) == 1 and akm[0].attrs["neg"] == -1e9
        got = sd.output(_MASK_FEEDS, ["p"])["p"]
        # post-softmax the select form is BITWISE identical: unmasked
        # scores pass through untouched, masked ones underflow to 0.0
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want))
        assert mask_strength_reduce(sd) == 0     # idempotent

    def test_shared_mask_broadcast_is_memoized(self):
        """N attention layers share one (1-m)*neg chain — the cloned
        mask broadcast must be emitted once, not once per layer."""
        import jax.numpy as jnp
        sd = SameDiff.create()
        m = sd.placeholder("m", shape=(2, 6), dtype=jnp.int32)
        mf = sd._op("cast", [m], {"dtype": "float32"})
        sub = sd._op("sub", [sd.constant("one", np.float32(1.0)), mf])
        mul = sd._op("mul", [sub, sd.constant("neg",
                                              np.float32(-1e9))])
        b = sd._op("expand_dims", [mul], {"axis": 1})
        b = sd._op("expand_dims", [b], {"axis": 2})
        for i in range(3):
            s = sd.placeholder(f"s{i}", shape=(2, 2, 4, 6))
            sd.nn.softmax(sd._op("add", [s, b])).rename(f"p{i}")
        assert mask_strength_reduce(sd) == 3
        masks = {o.inputs[1] for o in _ops(sd, "apply_key_mask")}
        assert len(masks) == 1
        clones = [o for o in sd.ops
                  if o.outputs[0].startswith("graphopt_mask")]
        assert len(clones) == 2                  # one chain, 2 hops

    def test_skips_non_binary_mask(self):
        import jax.numpy as jnp
        sd = _mask_graph(mask_dtype=jnp.float32)  # float provenance
        assert mask_strength_reduce(sd) == 0

    def test_skips_small_negative_constant(self):
        sd = _mask_graph(neg=-100.0)   # not provably underflowing
        assert mask_strength_reduce(sd) == 0

    def test_skips_multi_consumer_add(self):
        sd = _mask_graph(extra_add_consumer=True)
        assert mask_strength_reduce(sd) == 0


# ----------------------------------------------------- layernorm_refuse
def _ln_graph(form="tf", extra_mu_consumer=False):
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(2, 6, 8))
    g = sd.constant("g", R.rand(8).astype(np.float32) + 0.5)
    b = sd.constant("b", R.randn(8).astype(np.float32))
    mu = sd._op("reduce_mean", [x], {"axis": -1, "keep_dims": True})
    d = sd._op("sub", [x, mu])
    if form == "tf":
        sq = sd._op("squared_difference", [x, mu])
    else:
        sq = sd._op("pow", [d, sd.constant("two", np.float32(2.0))])
    var = sd._op("reduce_mean", [sq], {"axis": -1, "keep_dims": True})
    ve = sd._op("add", [var, sd.constant("eps", np.float32(1e-5))])
    if form == "tf":
        core = sd._op("mul", [d, sd._op("rsqrt", [ve])])
    else:
        core = sd._op("div", [d, sd._op("sqrt", [ve])])
    y = sd._op("add", [sd._op("mul", [core, g]), b]).rename("y")
    if extra_mu_consumer:
        sd._op("reduce_sum", [mu], {"axis": None}).rename("side")
    return sd


class TestLayerNormRefuse:
    @pytest.mark.parametrize("form", ["tf", "onnx"])
    def test_refuses_to_native_layer_norm(self, form):
        sd = _ln_graph(form)
        feeds = {"x": R.randn(2, 6, 8).astype(np.float32)}
        want = sd.output(feeds, ["y"])["y"]
        assert layernorm_refuse(sd) == 1
        ln = _ops(sd, "layer_norm")
        assert len(ln) == 1
        assert ln[0].inputs == ["x", "g", "b"]
        assert ln[0].attrs["epsilon"] == pytest.approx(1e-5)
        got = sd.output(feeds, ["y"])["y"]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        assert layernorm_refuse(sd) == 0         # idempotent

    def test_skips_shared_interior(self):
        sd = _ln_graph("tf", extra_mu_consumer=True)
        assert layernorm_refuse(sd) == 0


# --------------------------------------------------------- gelu_refuse
def _gelu_graph(form="erf"):
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(2, 8))
    half = sd.constant("half", np.float32(0.5))
    one = sd.constant("one", np.float32(1.0))
    if form == "erf":
        u = sd._op("div", [x, sd.constant(
            "sqrt2", np.float32(np.sqrt(2.0)))])
        inner = sd._op("erf", [u])
    else:
        c0 = sd.constant("c0", np.float32(0.7978845608028654))
        c1 = sd.constant("c1", np.float32(0.044715))
        x3 = sd._op("pow", [x, sd.constant("three", np.float32(3.0))])
        inner = sd._op("tanh", [sd._op("mul", [
            c0, sd._op("add", [x, sd._op("mul", [c1, x3])])])])
    sd._op("mul", [sd._op("mul", [x, half]),
                   sd._op("add", [one, inner])]).rename("y")
    return sd


class TestGeluRefuse:
    @pytest.mark.parametrize("form,opname", [("erf", "gelu"),
                                             ("tanh", "gelu_tanh")])
    def test_refuses_decomposed_gelu(self, form, opname):
        sd = _gelu_graph(form)
        feeds = {"x": R.randn(2, 8).astype(np.float32)}
        want = sd.output(feeds, ["y"])["y"]
        assert gelu_refuse(sd) == 1
        fused = _ops(sd, opname)
        assert len(fused) == 1 and fused[0].inputs == ["x"]
        got = sd.output(feeds, ["y"])["y"]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)
        assert gelu_refuse(sd) == 0              # idempotent


# ---------------------------------------------- attention_fuse (extended)
class TestAttentionFuseExtensions:
    def test_fuses_key_mask_form_to_sdpa_core(self):
        """mask_strength_reduce output feeds the fusion: the combined
        result is ONE sdpa_core in native key-mask mode."""
        import jax.numpy as jnp
        sd = SameDiff.create()
        q = sd.placeholder("q", shape=(2, 2, 6, 4))
        k = sd.placeholder("k", shape=(2, 2, 6, 4))
        v = sd.placeholder("v", shape=(2, 2, 6, 4))
        m = sd.placeholder("m", shape=(2, 6), dtype=jnp.int32)
        mf = sd._op("cast", [m], {"dtype": "float32"})
        sub = sd._op("sub", [sd.constant("one", np.float32(1.0)), mf])
        mul = sd._op("mul", [sub, sd.constant("neg",
                                              np.float32(-1e9))])
        b = sd._op("expand_dims", [mul], {"axis": 1})
        b = sd._op("expand_dims", [b], {"axis": 2})
        scores = sd._op("matmul", [q, k],
                        {"transpose_a": False, "transpose_b": True})
        scaled = sd._op("div", [scores, sd.constant(
            "c", np.float32(2.0))])
        probs = sd.nn.softmax(sd._op("add", [scaled, b]))
        sd._op("matmul", [probs, v]).rename("ctx")
        feeds = {"q": R.randn(2, 2, 6, 4).astype(np.float32),
                 "k": R.randn(2, 2, 6, 4).astype(np.float32),
                 "v": R.randn(2, 2, 6, 4).astype(np.float32),
                 "m": np.asarray([[1, 1, 1, 0, 0, 0],
                                  [1, 1, 1, 1, 1, 1]], np.int32)}
        want = sd.output(feeds, ["ctx"])["ctx"]
        counts = optimize(sd)
        assert counts["mask_strength_reduce"] == 1
        assert counts["attention_fuse"] == 1
        core = _ops(sd, "sdpa_core")[0]
        assert core.attrs == {"scale": 0.5, "mask_mode": "key"}
        assert len(core.inputs) == 4
        got = sd.output(feeds, ["ctx"])["ctx"]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_fuses_explicit_transpose_k_form(self):
        """The ONNX export spells k^T as Transpose(k, [..., -1, -2])
        before the MatMul — that form must fuse too."""
        sd = SameDiff.create()
        q = sd.placeholder("q", shape=(2, 2, 6, 4))
        k = sd.placeholder("k", shape=(2, 2, 6, 4))
        v = sd.placeholder("v", shape=(2, 2, 6, 4))
        kt = sd._op("transpose", [k], {"axes": [0, 1, 3, 2]})
        scores = sd._op("matmul", [q, kt])
        scaled = sd._op("mul", [scores, sd.constant(
            "c", np.float32(0.5))])
        probs = sd.nn.softmax(scaled)
        sd._op("matmul", [probs, v]).rename("ctx")
        feeds = {n: R.randn(2, 2, 6, 4).astype(np.float32)
                 for n in ("q", "k", "v")}
        want = sd.output(feeds, ["ctx"])["ctx"]
        assert attention_fuse(sd) == 1
        core = _ops(sd, "sdpa_core")[0]
        assert core.inputs == ["q", "k", "v"]
        got = sd.output(feeds, ["ctx"])["ctx"]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------- pipeline
class TestPipeline:
    def test_env_gate(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_GRAPHOPT", "0")
        assert not graphopt_enabled()
        monkeypatch.setenv("DL4J_TPU_GRAPHOPT", "1")
        assert graphopt_enabled()
        monkeypatch.delenv("DL4J_TPU_GRAPHOPT")
        assert graphopt_enabled()                # default on

    def test_telemetry_counter_and_dump(self, monkeypatch, capsys):
        monkeypatch.setenv("DL4J_TPU_DUMP_GRAPHOPT", "1")
        before = _REWRITES.value(**{"pass": "gelu_refuse"})
        sd = _gelu_graph("erf")
        counts = GraphOptimizer(sd).run()
        assert counts["gelu_refuse"] == 1
        after = _REWRITES.value(**{"pass": "gelu_refuse"})
        assert after == before + 1
        err = capsys.readouterr().err
        assert "[graphopt] before" in err
        assert "after gelu_refuse (+1)" in err

    def test_fixpoint_composes_passes(self):
        """cast folding must EXPOSE the mask chain: with the mask cast
        hidden behind an f32->f64->f32 round-trip the mask pass only
        fires after cast_fold unwinds it (same iteration, ordered
        pipeline)."""
        import jax.numpy as jnp
        sd = SameDiff.create()
        s = sd.placeholder("s", shape=(2, 2, 4, 6))
        m = sd.placeholder("m", shape=(2, 6), dtype=jnp.int32)
        mf = sd._op("cast", [m], {"dtype": "float32"})
        up = sd._op("cast", [mf], {"dtype": "float64"})
        dn = sd._op("cast", [up], {"dtype": "float32"})
        sub = sd._op("sub", [sd.constant("one", np.float32(1.0)), dn])
        mul = sd._op("mul", [sub, sd.constant("neg",
                                              np.float32(-1e9))])
        b = sd._op("expand_dims", [mul], {"axis": 1})
        b = sd._op("expand_dims", [b], {"axis": 2})
        sd.nn.softmax(sd._op("add", [s, b])).rename("p")
        want = sd.output(_MASK_FEEDS, ["p"])["p"]
        counts = optimize(sd)
        assert counts["cast_fold"] >= 2
        assert counts["mask_strength_reduce"] == 1
        got = sd.output(_MASK_FEEDS, ["p"])["p"]
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want))
        # whole-pipeline idempotency
        assert sum(optimize(sd).values()) == 0


# --------------------------------------- exactness: real TF BERT import
class TestImportedBertExactness:
    def test_optimized_import_matches_plain_loss_and_trajectory(self):
        pytest.importorskip("tensorflow")
        from benchmarks.tf_bert_builder import (build_frozen_bert,
                                                import_and_attach_mlm)
        from deeplearning4j_tpu.learning import Adam
        vocab, hidden, heads, layers, seq, batch = 50, 16, 2, 2, 16, 2
        gd, _ = build_frozen_bert(seq, batch, vocab=vocab,
                                  hidden=hidden, heads=heads,
                                  layers=layers, intermediate=32)
        rs = np.random.RandomState(1)
        feeds = {
            "ids": rs.randint(0, vocab, (batch, seq)).astype(np.int32),
            "seg": np.zeros((batch, seq), np.int32),
            "mask": np.concatenate(
                [np.ones((batch, seq - 3), np.int32),
                 np.zeros((batch, 3), np.int32)], axis=1),
            "mlm_labels": np.where(rs.rand(batch, seq) < 0.3,
                                   rs.randint(0, vocab, (batch, seq)),
                                   -1).astype(np.int32)}

        plain, loss = import_and_attach_mlm(
            gd, batch, seq, vocab=vocab, hidden=hidden,
            updater=Adam(1e-3), optimize=False)
        opt, _ = import_and_attach_mlm(
            gd, batch, seq, vocab=vocab, hidden=hidden,
            updater=Adam(1e-3))

        # every transformer pass fires on the real frozen graph
        c = opt.graphopt_counts
        assert c["mask_strength_reduce"] == layers
        assert c["layernorm_refuse"] == 2 * layers
        assert c["gelu_refuse"] == layers
        assert c["attention_fuse"] == layers

        want = plain.output(feeds, [loss])[loss]
        got = opt.output(feeds, [loss])[loss]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        lp = plain.fit_steps(feeds, 4)
        lo = opt.fit_steps(feeds, 4)
        np.testing.assert_allclose(lo, lp, rtol=1e-4, atol=1e-5)


# ------------------------------- exactness: hand-encoded ONNX transformer
def _onnx_encoder(batch=2, seq=8, hidden=16, heads=2, layers=2,
                  ffn=32, seed=0):
    """A HF-style ONNX transformer encoder, hand-encoded with the
    in-repo protobuf writer: explicit Transpose(k), Div-scaled scores,
    Cast(int64 mask) -> Sub/Mul(-1e4)/Unsqueeze additive bias, the
    Sub/Pow/Sqrt/Div LayerNorm decomposition, the Div/Erf GELU
    spelling, plus a dead f32->f64->f32 round-trip on the input."""
    from deeplearning4j_tpu.modelimport.onnx.protobuf import (
        encode_model, encode_node, encode_value_info)
    rs = np.random.RandomState(seed)
    hd = hidden // heads
    inits, nodes = {}, []

    def W(name, *shape, scale=0.05):
        inits[name] = (rs.randn(*shape) * scale).astype(np.float32)
        return name

    inits["mask_i64"] = np.asarray(
        [[1] * seq, [1] * (seq - 3) + [0] * 3], np.int64)
    for n, v in (("c_one", 1.0), ("c_half", 0.5), ("c_two", 2.0),
                 ("c_eps", 1e-5), ("c_neg", -1e4),
                 ("c_sqrt2", float(np.sqrt(2.0))),
                 ("c_sqrt_hd", float(np.sqrt(hd)))):
        inits[n] = np.float32(v)
    inits["shape_split"] = np.asarray([batch, seq, heads, hd],
                                      np.int64)
    inits["shape_merge"] = np.asarray([batch, seq, hidden], np.int64)

    # input round-trip (dead dtype arithmetic exporters bake in)
    nodes += [encode_node("Cast", ["x"], ["x_up"], "cu", to=11),
              encode_node("Cast", ["x_up"], ["h"], "cd", to=1)]
    # shared additive attention-mask chain
    nodes += [
        encode_node("Cast", ["mask_i64"], ["m_f"], "mc", to=1),
        encode_node("Sub", ["c_one", "m_f"], ["m_inv"], "ms"),
        encode_node("Mul", ["m_inv", "c_neg"], ["m_neg"], "mm"),
        encode_node("Unsqueeze", ["m_neg"], ["m_bias"], "mu",
                    axes=[1, 2]),
    ]

    cur = "h"
    for i in range(layers):
        p = f"l{i}_"

        def proj(nm):
            W(f"{p}W{nm}", hidden, hidden)
            W(f"{p}b{nm}", hidden, scale=0.0)
            nodes.extend([
                encode_node("MatMul", [cur, f"{p}W{nm}"],
                            [f"{p}{nm}mm"], f"{p}{nm}0"),
                encode_node("Add", [f"{p}{nm}mm", f"{p}b{nm}"],
                            [f"{p}{nm}a"], f"{p}{nm}1"),
                encode_node("Reshape", [f"{p}{nm}a", "shape_split"],
                            [f"{p}{nm}r"], f"{p}{nm}2"),
                encode_node("Transpose", [f"{p}{nm}r"],
                            [f"{p}{nm}t"], f"{p}{nm}3",
                            perm=[0, 2, 1, 3]),
            ])
            return f"{p}{nm}t"

        q, k, v = proj("q"), proj("k"), proj("v")
        W(f"{p}Wo", hidden, hidden)
        W(f"{p}bo", hidden, scale=0.0)
        nodes += [
            encode_node("Transpose", [k], [f"{p}kT"], f"{p}a0",
                        perm=[0, 1, 3, 2]),
            encode_node("MatMul", [q, f"{p}kT"], [f"{p}sc"], f"{p}a1"),
            encode_node("Div", [f"{p}sc", "c_sqrt_hd"], [f"{p}scd"],
                        f"{p}a2"),
            encode_node("Add", [f"{p}scd", "m_bias"], [f"{p}scm"],
                        f"{p}a3"),
            encode_node("Softmax", [f"{p}scm"], [f"{p}pr"], f"{p}a4",
                        axis=-1),
            encode_node("MatMul", [f"{p}pr", v], [f"{p}cx"], f"{p}a5"),
            encode_node("Transpose", [f"{p}cx"], [f"{p}cxt"],
                        f"{p}a6", perm=[0, 2, 1, 3]),
            encode_node("Reshape", [f"{p}cxt", "shape_merge"],
                        [f"{p}cxr"], f"{p}a7"),
            encode_node("MatMul", [f"{p}cxr", f"{p}Wo"], [f"{p}om"],
                        f"{p}a8"),
            encode_node("Add", [f"{p}om", f"{p}bo"], [f"{p}oa"],
                        f"{p}a9"),
            encode_node("Add", [cur, f"{p}oa"], [f"{p}res1"],
                        f"{p}a10"),
        ]

        def ln(tag, src, dst):
            g = f"{p}g{tag}"
            b = f"{p}be{tag}"
            inits[g] = np.ones(hidden, np.float32)
            inits[b] = np.zeros(hidden, np.float32)
            t = f"{p}{tag}"
            nodes.extend([
                encode_node("ReduceMean", [src], [f"{t}mu"],
                            f"{t}n0", axes=[-1], keepdims=1),
                encode_node("Sub", [src, f"{t}mu"], [f"{t}d"],
                            f"{t}n1"),
                encode_node("Pow", [f"{t}d", "c_two"], [f"{t}dd"],
                            f"{t}n2"),
                encode_node("ReduceMean", [f"{t}dd"], [f"{t}var"],
                            f"{t}n3", axes=[-1], keepdims=1),
                encode_node("Add", [f"{t}var", "c_eps"], [f"{t}ve"],
                            f"{t}n4"),
                encode_node("Sqrt", [f"{t}ve"], [f"{t}sd"], f"{t}n5"),
                encode_node("Div", [f"{t}d", f"{t}sd"], [f"{t}nr"],
                            f"{t}n6"),
                encode_node("Mul", [f"{t}nr", g], [f"{t}sg"],
                            f"{t}n7"),
                encode_node("Add", [f"{t}sg", b], [dst], f"{t}n8"),
            ])

        ln("ln1", f"{p}res1", f"{p}x1")
        W(f"{p}W1", hidden, ffn)
        W(f"{p}b1", ffn, scale=0.0)
        W(f"{p}W2", ffn, hidden)
        W(f"{p}b2", hidden, scale=0.0)
        nodes += [
            encode_node("MatMul", [f"{p}x1", f"{p}W1"], [f"{p}h1"],
                        f"{p}f0"),
            encode_node("Add", [f"{p}h1", f"{p}b1"], [f"{p}hb"],
                        f"{p}f1"),
            encode_node("Div", [f"{p}hb", "c_sqrt2"], [f"{p}gd"],
                        f"{p}f2"),
            encode_node("Erf", [f"{p}gd"], [f"{p}ge"], f"{p}f3"),
            encode_node("Add", [f"{p}ge", "c_one"], [f"{p}g1"],
                        f"{p}f4"),
            encode_node("Mul", [f"{p}hb", "c_half"], [f"{p}gh"],
                        f"{p}f5"),
            encode_node("Mul", [f"{p}gh", f"{p}g1"], [f"{p}gel"],
                        f"{p}f6"),
            encode_node("MatMul", [f"{p}gel", f"{p}W2"], [f"{p}h2"],
                        f"{p}f7"),
            encode_node("Add", [f"{p}h2", f"{p}b2"], [f"{p}hb2"],
                        f"{p}f8"),
            encode_node("Add", [f"{p}x1", f"{p}hb2"], [f"{p}res2"],
                        f"{p}f9"),
        ]
        ln("ln2", f"{p}res2", f"{p}out" if i < layers - 1 else "y")
        cur = f"{p}out"

    model = encode_model(
        nodes, inits,
        [encode_value_info("x", (batch, seq, hidden))],
        [encode_value_info("y", (batch, seq, hidden))])
    wnames = [n for n in inits
              if n.startswith("l") and inits[n].ndim >= 1]
    return model, wnames


def _onnx_trainable(model, wnames, optimize_flag):
    from deeplearning4j_tpu.autodiff.samediff import VariableType
    from deeplearning4j_tpu.autodiff.training import TrainingConfig
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.modelimport.onnx import import_onnx
    imp = import_onnx(model, optimize=optimize_flag)
    sd = imp.sd
    wset = set(wnames)
    promote = [n for n, v in sd.vars.items()
               if v.var_type == VariableType.CONSTANT
               and n.split("__")[0] in wset]
    assert len(promote) == len(wset)
    sd.convert_to_variables(promote)
    yv = imp.var_map["y"]
    sq = sd._op("mul", [yv, yv])
    sd._op("reduce_sum", [sq], {"axis": None}).rename("loss")
    sd.set_loss_variables(["loss"])
    sd.set_training_config(
        TrainingConfig.Builder().updater(Adam(1e-3)).build())
    return imp, sd


class TestImportedOnnxExactness:
    def test_optimized_import_matches_plain_loss_and_trajectory(self):
        layers = 2
        model, wnames = _onnx_encoder(layers=layers)
        feeds = {"x": np.random.RandomState(2)
                 .randn(2, 8, 16).astype(np.float32)}

        _, plain = _onnx_trainable(model, wnames, False)
        impo, opt = _onnx_trainable(model, wnames, None)

        c = impo.sd.graphopt_counts
        assert c["cast_fold"] >= 2               # the x round-trip
        assert c["mask_strength_reduce"] == layers
        assert c["layernorm_refuse"] == 2 * layers
        assert c["gelu_refuse"] == layers
        assert c["attention_fuse"] == layers

        want = plain.output(feeds, ["loss"])["loss"]
        got = opt.output(feeds, ["loss"])["loss"]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        lp = plain.fit_steps(feeds, 4)
        lo = opt.fit_steps(feeds, 4)
        np.testing.assert_allclose(lo, lp, rtol=1e-4, atol=1e-5)
