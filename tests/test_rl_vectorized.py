"""RL depth (SURVEY.md D18; round-2 verdict ask #7): vectorized
multi-env A3C with a LEARNING-CURVE GATE — CartPole must actually
solve — plus batched-env physics parity and the external env-binding
seam."""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu.rl import (A3CVectorized,
                                   A3CVectorizedConfiguration,
                                   CartPole, GymMDPAdapter,
                                   QLearningConfiguration,
                                   QLearningDiscreteDense,
                                   VectorCartPole)


class TestVectorEnvParity:
    def test_batched_physics_match_scalar_cartpole(self):
        """One env of the batched dynamics must track mdp.CartPole
        exactly for a shared action sequence (no done resets)."""
        venv = VectorCartPole(n_envs=3, max_steps=500)
        key = jax.random.PRNGKey(0)
        state = venv.reset(key)
        scalar = CartPole(seed=0, max_steps=500)
        scalar.reset()
        # force identical starting state for env 0
        s0 = np.asarray(state["s"][0], np.float64)
        scalar._state = s0.copy()
        rng = np.random.RandomState(3)
        for t in range(30):
            a = int(rng.randint(0, 2))
            acts = jax.numpy.asarray([a, 1 - a, a])
            state, r, d, _ = venv.step(state, acts,
                                       jax.random.PRNGKey(t + 1))
            reply = scalar.step(a)
            if bool(d[0]) or reply.done:
                assert bool(d[0]) == reply.done
                break
            np.testing.assert_allclose(np.asarray(state["s"][0]),
                                       scalar._state, atol=1e-5)


class TestLearningCurveGate:
    def test_cartpole_solved(self):
        """The verdict's acceptance bar: the CartPole solved-threshold
        gate passes — greedy eval ≥ 195/200 (the classic gym solved
        criterion) within a bounded training budget."""
        env = VectorCartPole(n_envs=16, max_steps=200)
        agent = A3CVectorized(env, A3CVectorizedConfiguration(seed=7))
        score = 0.0
        for _ in range(8):                 # ≤1600 updates
            agent.train(200)
            score = agent.evaluate(n_episodes=5)
            if score >= 195.0:
                break
        assert score >= 195.0, f"CartPole not solved: eval={score}"
        # confirm on a fresh, larger eval
        assert agent.evaluate(n_episodes=10) >= 195.0

    def test_training_collects_episode_rewards(self):
        env = VectorCartPole(n_envs=8, max_steps=100)
        agent = A3CVectorized(env, A3CVectorizedConfiguration(
            seed=1, n_envs=8))
        fin = agent.train(30)
        assert len(fin) > 0
        assert all(1.0 <= f <= 100.0 for f in fin)


class _FakeGym4:
    """Classic gym API: 4-tuple step, bare-obs reset."""

    class _Space:
        def __init__(self, shape=None, n=None):
            self.shape = shape
            self.n = n

    def __init__(self):
        self.observation_space = self._Space(shape=(3,))
        self.action_space = self._Space(n=2)
        self._t = 0
        self.closed = False

    def reset(self):
        self._t = 0
        return np.zeros(3)

    def step(self, action):
        self._t += 1
        obs = np.full(3, self._t, np.float64)
        return obs, float(action), self._t >= 5, {}

    def close(self):
        self.closed = True


class _FakeGym5(_FakeGym4):
    """gymnasium API: 5-tuple step, (obs, info) reset."""

    def reset(self):
        self._t = 0
        return np.zeros(3), {}

    def step(self, action):
        self._t += 1
        obs = np.full(3, self._t, np.float64)
        return obs, float(action), False, self._t >= 4, {}


class TestEnvBindingSeam:
    @pytest.mark.parametrize("env_cls,horizon", [(_FakeGym4, 5),
                                                 (_FakeGym5, 4)])
    def test_adapter_contract(self, env_cls, horizon):
        mdp = GymMDPAdapter(env_cls())
        assert mdp.obs_size == 3 and mdp.n_actions == 2
        obs = mdp.reset()
        assert obs.dtype == np.float32 and obs.shape == (3,)
        steps = 0
        while not mdp.is_done():
            reply = mdp.step(1)
            assert reply.reward == 1.0
            steps += 1
        assert steps == horizon
        mdp.close()
        assert mdp._env.closed

    def test_dqn_trains_through_adapter(self):
        """The DQN learner accepts an adapted external env (the
        reference's GymEnv role)."""

        class _Corridor(_FakeGym4):
            def __init__(self):
                super().__init__()
                self.observation_space = self._Space(shape=(4,))
                self.pos = 0

            def reset(self):
                self.pos = 0
                return self._obs()

            def _obs(self):
                o = np.zeros(4)
                o[self.pos] = 1.0
                return o

            def step(self, action):
                self.pos = max(0, min(3, self.pos
                                      + (1 if action == 1 else -1)))
                done = self.pos == 3
                return self._obs(), 1.0 if done else 0.0, done, {}

        mdp = GymMDPAdapter(_Corridor())
        learner = QLearningDiscreteDense(
            mdp, QLearningConfiguration(seed=3, max_step=1500))
        learner.train()
        policy = learner.get_policy()
        obs = mdp.reset()
        for _ in range(3):
            a = policy.next_action(obs)
            assert a == 1                    # learned: always go right
            obs = mdp.step(a).observation
        assert mdp.is_done()
