"""Native C++ host-runtime tests (SURVEY.md §2.7: threshold codec,
CRC, workspace arena, async queue, CSV fast path, toposort).

The library auto-builds with the container's g++; every API also has
a pure-Python fallback exercised via DL4J_TPU_DISABLE_NATIVE."""
import threading
import zlib

import numpy as np
import pytest

from deeplearning4j_tpu import native as nat


@pytest.fixture(scope="module")
def built():
    ok = nat.ensure_built()
    if not ok:
        pytest.skip("native lib unavailable (no compiler?)")
    return ok


class TestCrc32:
    def test_matches_zlib(self, built):
        for payload in [b"", b"a", b"hello world" * 100,
                        np.arange(1000, dtype=np.float32).tobytes()]:
            assert nat.crc32(payload) == zlib.crc32(payload) & 0xFFFFFFFF


class TestThresholdCodec:
    def test_roundtrip(self, built):
        rng = np.random.RandomState(0)
        g = rng.randn(10_000).astype(np.float32) * 0.01
        tau = 0.015
        enc = nat.threshold_encode(g, tau)
        # every encoded index has |g| >= tau
        idx = np.abs(enc) - 1
        assert (np.abs(g[idx]) >= tau).all()
        assert len(enc) == int((np.abs(g) >= tau).sum())
        dec = nat.threshold_decode(enc, tau, g.size)
        np.testing.assert_allclose(dec[idx],
                                   np.sign(g[idx]) * tau, atol=1e-7)
        assert dec[np.setdiff1d(np.arange(g.size), idx)].sum() == 0

    def test_residual(self, built):
        rng = np.random.RandomState(1)
        g = rng.randn(1000).astype(np.float32) * 0.01
        tau = 0.012
        enc = nat.threshold_encode(g, tau)
        res = g.copy()
        nat.threshold_residual(res, enc, tau)
        # residual + decoded == original gradient
        np.testing.assert_allclose(
            res + nat.threshold_decode(enc, tau, g.size), g, atol=1e-6)

    def test_matches_python_fallback(self, built, monkeypatch):
        rng = np.random.RandomState(2)
        g = rng.randn(5000).astype(np.float32) * 0.02
        enc_native = nat.threshold_encode(g, 0.03)
        monkeypatch.setenv("DL4J_TPU_DISABLE_NATIVE", "1")
        from deeplearning4j_tpu.native import bridge
        monkeypatch.setattr(bridge, "_lib", None)
        monkeypatch.setattr(bridge, "_build_attempted", True)
        enc_py = nat.threshold_encode(g, 0.03)
        np.testing.assert_array_equal(enc_native, enc_py)


class TestToposort:
    def test_valid_order(self, built):
        edges = [(0, 2), (1, 2), (2, 3), (1, 3), (3, 4)]
        order = nat.toposort(edges, 5)
        pos = {n: i for i, n in enumerate(order)}
        assert sorted(order) == [0, 1, 2, 3, 4]
        for s, d in edges:
            assert pos[s] < pos[d]

    def test_cycle_raises(self, built):
        with pytest.raises(ValueError, match="cycle"):
            nat.toposort([(0, 1), (1, 2), (2, 0)], 3)

    def test_empty(self, built):
        assert nat.toposort([], 0) == []


class TestCsv:
    def test_parse_matrix(self, built):
        text = "1.5,2,3\n-4,5e-2,6\n7,8,9.25\n"
        m = nat.parse_csv_floats(text)
        np.testing.assert_allclose(
            m, [[1.5, 2, 3], [-4, 0.05, 6], [7, 8, 9.25]])

    def test_ragged_raises(self, built):
        with pytest.raises(ValueError, match="ragged"):
            nat.parse_csv_floats("1,2\n3,4,5\n")

    def test_empty_trailing_field_keeps_row_boundary(self, built):
        """Regression: an empty field before a newline must become NaN
        in place, not let the parser eat the newline and merge rows."""
        m = nat.parse_csv_floats("1,\n3,4\n")
        assert m.shape == (2, 2)
        assert m[0, 0] == 1.0 and np.isnan(m[0, 1])
        np.testing.assert_allclose(m[1], [3, 4])
        m2 = nat.parse_csv_floats("1, \n , 2\n")   # whitespace fields
        assert m2.shape == (2, 2)
        assert np.isnan(m2[0, 1]) and np.isnan(m2[1, 0])

    def test_no_trailing_newline(self, built):
        m = nat.parse_csv_floats("1,2\n3,4")
        np.testing.assert_allclose(m, [[1, 2], [3, 4]])

    def test_blank_interior_lines_skipped(self, built):
        """Native and fallback must agree: blank lines filtered."""
        m = nat.parse_csv_floats("1,2\n\n3,4\n\n")
        np.testing.assert_allclose(m, [[1, 2], [3, 4]])
        # whitespace-only lines count as blank too (fallback strips)
        m2 = nat.parse_csv_floats("1,2\n \n3,4\n\t\n")
        np.testing.assert_allclose(m2, [[1, 2], [3, 4]])

    def test_non_numeric_field_is_nan_both_paths(self, built,
                                                 monkeypatch):
        m = nat.parse_csv_floats("a,2\n3,4\n")
        assert np.isnan(m[0, 0]) and m[0, 1] == 2
        monkeypatch.setenv("DL4J_TPU_DISABLE_NATIVE", "1")
        from deeplearning4j_tpu.native import bridge
        monkeypatch.setattr(bridge, "_lib", None)
        monkeypatch.setattr(bridge, "_build_attempted", True)
        m2 = nat.parse_csv_floats("a,2\n3,4\n")
        assert np.isnan(m2[0, 0]) and m2[0, 1] == 2

    def test_decode_rejects_bad_out_buffer(self, built):
        enc = nat.threshold_encode(
            np.array([1.0, -1.0], np.float32), 0.5)
        with pytest.raises(ValueError, match="float32"):
            nat.threshold_decode(enc, 0.5, 2,
                                 out=np.zeros(2, np.float64))
        with pytest.raises(ValueError, match="size"):
            nat.threshold_decode(enc, 0.5, 2,
                                 out=np.zeros(1, np.float32))

    def test_record_reader_fast_path(self, built, tmp_path):
        p = tmp_path / "data.csv"
        rows = np.arange(30, dtype=np.float32).reshape(10, 3)
        p.write_text("\n".join(",".join(str(v) for v in r)
                               for r in rows))
        from deeplearning4j_tpu.datavec.records import CSVRecordReader
        from deeplearning4j_tpu.datavec.split import FileSplit
        rr = CSVRecordReader()
        m = rr.numeric_matrix(FileSplit(str(p)))
        np.testing.assert_allclose(m, rows)


class TestQueue:
    def test_fifo_and_blocking(self, built):
        q = nat.NativeQueue(4)
        items = list(range(100))
        out = []

        def producer():
            for i in items:
                q.put(("item", i))
            q.put(None)  # sentinel

        t = threading.Thread(target=producer)
        t.start()
        while True:
            obj = q.get(timeout=5.0)
            if obj is None:
                break
            out.append(obj[1])
        t.join()
        assert out == items

    def test_timeout(self, built):
        import queue as pyq
        q = nat.NativeQueue(2)
        with pytest.raises(pyq.Empty):
            q.get(timeout=0.05)
        q.put(1)
        q.put(2)
        assert not q.put(3, timeout=0.05)   # full -> timed out

    def test_close_unblocks(self, built):
        q = nat.NativeQueue(2)
        errs = []

        def getter():
            try:
                q.get(timeout=5.0)
            except StopIteration:
                errs.append("stopped")

        t = threading.Thread(target=getter)
        t.start()
        q.close()
        t.join(timeout=5.0)
        assert errs == ["stopped"]


class TestArena:
    def test_alloc_reset_reuse(self, built):
        with nat.arena(1 << 16) as ws:
            a = ws.alloc((64,), np.float32)
            a[:] = 3.0
            used1 = ws.used
            assert used1 >= 64 * 4
            b = ws.alloc((32,), np.int32)
            b[:] = 7
            assert ws.used > used1
            ws.reset()
            assert ws.used == 0
            c = ws.alloc((64,), np.float32)
            # same storage reused after reset (native path)
            assert ws.used == used1
            assert ws.high_water >= used1

    def test_spill_beyond_capacity(self, built):
        ws = nat.arena(128)
        big = ws.alloc((1024,), np.float32)   # > capacity -> spill
        big[:] = 1.0
        assert big.shape == (1024,)

    def test_escaping_view_pins_arena(self, built):
        """A view outliving its arena must keep the malloc block
        alive (no use-after-free)."""
        import gc
        a = nat.arena(1 << 12).alloc((64,), np.float32)
        gc.collect()
        a[:] = 7.0                      # would corrupt freed memory
        assert (np.asarray(a) == 7.0).all()


class TestAsyncIterator:
    def test_streams_all_batches(self, built):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import (
            AsyncDataSetIterator, ListDataSetIterator)
        x = np.arange(80, dtype=np.float32).reshape(20, 4)
        y = np.eye(2, dtype=np.float32)[np.arange(20) % 2]
        base = ListDataSetIterator(DataSet(x, y), 5)
        it = AsyncDataSetIterator(base, queue_size=2)
        seen = [ds.features[0, 0].item() for ds in it]
        assert seen == [0.0, 20.0, 40.0, 60.0]
        # reset + re-iterate works
        seen2 = [ds.features[0, 0].item() for ds in it]
        assert seen2 == seen
