"""Layer-attribution observatory tests (ISSUE 14): scope annotation
semantics and gating, the analytic HLO partition (incl. while-loop
trip counts), static/dynamic attribution and their reconciliation
contract, the kernel-decision join, and the report surfaces
(``/api/layers``, flight-recorder ``top_layer``, ``dl4j_layer_*``
metrics)."""
import json
import urllib.error
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

from deeplearning4j_tpu.common import layerprof, telemetry
from deeplearning4j_tpu.common.environment import Environment


@pytest.fixture(autouse=True)
def _fresh_layerprof():
    layerprof.reset()
    Environment.get().extra.pop("layerprof", None)
    yield
    layerprof.reset()
    Environment.get().extra.pop("layerprof", None)


def _tiny_net_and_data():
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    rng = np.random.RandomState(0)
    x = rng.randn(16, 8).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 16)]
    net = MultiLayerNetwork(
        (NeuralNetConfiguration.Builder().seed(1).updater(Adam(1e-3))
         .list()
         .layer(DenseLayer(n_out=16, activation=Activation.RELU))
         .layer(OutputLayer(n_out=4, activation=Activation.SOFTMAX,
                            loss_function=LossFunction.MCXENT))
         .set_input_type(InputType.feed_forward(8)).build())).init()
    return net, x, y


class TestScope:
    def test_sanitize(self):
        assert layerprof.sanitize("layer_0") == "layer_0"
        assert layerprof.sanitize("conv 1x1/a!") == "conv_1x1_a_"
        assert layerprof.sanitize("enc.ffn") == "enc.ffn"
        assert layerprof.sanitize("") == "_"

    def test_scope_stack_nests_and_pops(self):
        assert layerprof.current_scope() is None
        with layerprof.scope("outer"):
            assert layerprof.current_scope() == "outer"
            with layerprof.scope("inner x"):
                assert layerprof.current_scope() == "inner_x"
            assert layerprof.current_scope() == "outer"
        assert layerprof.current_scope() is None

    def test_gate_off_is_a_null_scope(self):
        Environment.get().extra["layerprof"] = False
        assert not layerprof.enabled()
        with layerprof.scope("ghost"):
            assert layerprof.current_scope() is None
        Environment.get().extra["layerprof"] = True
        assert layerprof.enabled()

    def test_env_var_gate(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_LAYERPROF", "0")
        assert not layerprof.enabled()
        # Environment.extra overrides the env var
        Environment.get().extra["layerprof"] = True
        assert layerprof.enabled()


class TestKernelJoin:
    def test_note_selection_joins_on_active_scope(self):
        sel = SimpleNamespace(kernel="conv_epilogue", fused=True,
                              decision="heuristic", reason="big tile")
        with layerprof.scope("layer_3"):
            layerprof.note_selection(sel)
            layerprof.note_selection(sel)
        got = layerprof.kernel_decisions("layer_3")
        assert got["conv_epilogue"]["fused"] is True
        assert got["conv_epilogue"]["decision"] == "heuristic"
        assert got["conv_epilogue"]["sites"] == 2
        # outside any scope the decision still lands somewhere visible
        layerprof.note_selection(SimpleNamespace(
            kernel="flash", fused=False, decision="structural",
            reason="seq too short"))
        assert "flash" in layerprof.kernel_decisions("_unscoped")


_SCAN_HLO = """\
%body (p: (s32[], f32[4,4])) -> (s32[], f32[4,4]) {
  %p = (s32[], f32[4,4]) parameter(0)
  %a = f32[4,4] get-tuple-element(%p), index=1
  %d = f32[4,4] dot(%a, %a), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(f)/dl4j.scan_layer/dot"}
  %i = s32[] get-tuple-element(%p), index=0
  ROOT %t = (s32[], f32[4,4]) tuple(%i, %d)
}

%cond (p.1: (s32[], f32[4,4])) -> pred[] {
  %p.1 = (s32[], f32[4,4]) parameter(0)
  %i.1 = s32[] get-tuple-element(%p.1), index=0
  %trip = s32[] constant(8)
  ROOT %lt = pred[] compare(%i.1, %trip), direction=LT
}

ENTRY %main (x: f32[4,4]) -> f32[4,4] {
  %x = f32[4,4] parameter(0)
  %d0 = f32[4,4] dot(%x, %x), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(f)/dl4j.head/dot"}
  %w = (s32[], f32[4,4]) while(%d0), condition=%cond, body=%body
  ROOT %r = f32[4,4] get-tuple-element(%w), index=1
}
"""


class TestHloParser:
    def test_while_body_weighted_by_trip_count(self):
        """A lax.scan-shaped while must charge its body per executed
        trip: the dot inside an 8-trip loop costs 8x the identical
        entry-level dot; the cond's comparison work stays free."""
        costs = layerprof.parse_hlo(_SCAN_HLO)
        # 4x4 @ 4x4 dot = 2*16*4 = 128 flops
        assert costs["head"].flops_fwd == pytest.approx(128.0)
        assert costs["scan_layer"].flops_fwd == pytest.approx(8 * 128.0)

    def test_transpose_opname_lands_in_bwd(self):
        hlo = _SCAN_HLO.replace(
            'op_name="jit(f)/dl4j.head/dot"',
            'op_name="jit(f)/transpose(dl4j.head)/dot"')
        costs = layerprof.parse_hlo(hlo)
        assert costs["head"].flops_bwd == pytest.approx(128.0)
        assert costs["head"].flops_fwd == 0.0


class TestStaticAttribution:
    def test_jitted_fn_partition_reconciles(self):
        import jax
        import jax.numpy as jnp

        def loss(w1, w2, x):
            with layerprof.scope("dense1"):
                h = jnp.tanh(x @ w1)
            with layerprof.scope("dense2"):
                o = h @ w2
            return (o * o).sum()

        rng = np.random.RandomState(0)
        args = (jnp.asarray(rng.randn(32, 64), jnp.float32),
                jnp.asarray(rng.randn(64, 16), jnp.float32),
                jnp.asarray(rng.randn(8, 32), jnp.float32))
        compiled = jax.jit(
            jax.value_and_grad(loss, argnums=(0, 1))
        ).lower(*args).compile()
        rep = layerprof.attribute_compiled(compiled, model_name="toy")

        for name in ("dense1", "dense2"):
            ent = rep["layers"][name]
            assert ent["flops_fwd"] > 0, name
            assert ent["flops_bwd"] > 0, name
            assert ent["bound"] in ("compute", "hbm")
            assert ent["est_ms"] > 0
        # the contract the CI gate sells: per-layer sums == totals
        assert layerprof.reconcile_error_pct(rep) < 1.0
        assert rep["coverage"]["flops"] > 0.5
        assert rep["time_source"] == "static_roofline_model"
        # publication side effects
        assert layerprof.last_report() is rep
        assert layerprof.top_layer() in rep["layers"]

    def test_mln_layer_report_and_surfaces(self):
        from deeplearning4j_tpu.common import diagnostics
        net, x, y = _tiny_net_and_data()
        rep = net.layer_report(x, y)
        assert {"layer_0", "layer_1"} <= set(rep["layers"])
        assert layerprof.reconcile_error_pct(rep) < 1.0
        for name in ("layer_0", "layer_1"):
            ent = rep["layers"][name]
            assert ent["flops_fwd"] > 0 and ent["flops_bwd"] > 0
            # the dl4j_layer_* gauges track the report
            assert telemetry.gauge(
                "dl4j_layer_flops", "x").value(layer=name) \
                == ent["flops"]
            assert telemetry.gauge(
                "dl4j_layer_bytes", "x").value(layer=name) \
                == ent["bytes"]
        # flight-recorder records stamp the heaviest layer
        assert layerprof.top_layer() is not None
        fr = diagnostics.FlightRecorder.get()
        fr.record(net, "test", 0, 0.5)
        assert fr.records()[-1]["top_layer"] == layerprof.top_layer()


class TestDynamicAttribution:
    def _events(self):
        return [
            {"name": "dl4j.layer_0", "ph": "X", "ts": 0, "dur": 2000},
            {"name": "fusion.7", "ph": "X", "ts": 10, "dur": 1000,
             "args": {"op_name": "jit(step)/dl4j.layer_0/dot"}},
            {"name": "transpose(dl4j.layer_0)", "ph": "X", "ts": 20,
             "dur": 4000},
            {"name": "dl4j.layer_1", "ph": "B", "ts": 30},  # not ph=X
            {"name": "no_scope_here", "ph": "X", "ts": 40, "dur": 99},
        ]

    def test_attribute_trace_buckets_and_observes(self):
        before = telemetry.histogram(
            "dl4j_layer_seconds", "x").count_of(
            layer="layer_0", **{"pass": "fwd"})
        out = layerprof.attribute_trace(self._events())
        assert set(out) == {"layer_0"}
        assert out["layer_0"]["fwd_ms"] == pytest.approx(3.0)
        assert out["layer_0"]["bwd_ms"] == pytest.approx(4.0)
        after = telemetry.histogram(
            "dl4j_layer_seconds", "x").count_of(
            layer="layer_0", **{"pass": "fwd"})
        assert after == before + 1

    def test_share_step_time_and_join(self):
        net, x, y = _tiny_net_and_data()
        rep = net.layer_report(x, y)
        split = layerprof.share_step_time(rep, 10.0)
        # the measured wall time is conserved across the split
        total = sum(m["fwd_ms"] + m["bwd_ms"] for m in split.values())
        assert total == pytest.approx(10.0, rel=1e-6)
        assert rep["time_source"] == "static_share_proxy"
        for name in ("layer_0", "layer_1"):
            ent = rep["layers"][name]
            assert ent["fwd_ms"] + ent["bwd_ms"] > 0
            assert ent["pct_of_roof"] is not None
        # explicit join path: measured ms replace the shares
        rep2 = layerprof.join_dynamic(
            rep, {"layer_0": {"fwd_ms": 1.0, "bwd_ms": 2.0}},
            time_source="trace")
        assert rep2["layers"]["layer_0"]["fwd_ms"] == 1.0
        assert rep2["time_source"] == "trace"


class TestApiLayers:
    def test_endpoint_404_then_report(self):
        from deeplearning4j_tpu.ui.server import UIServer
        ui = UIServer()                  # fresh instance, not the
        ui.start(port=0)                 # singleton: tests stay isolated
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(ui.url + "/api/layers")
            assert ei.value.code == 404
            assert "no layer report" in json.loads(
                ei.value.read().decode())["error"]

            net, x, y = _tiny_net_and_data()
            rep = net.layer_report(x, y)
            with urllib.request.urlopen(ui.url + "/api/layers") as r:
                assert r.status == 200
                body = json.loads(r.read().decode())
            assert set(body["layers"]) == set(rep["layers"])
            assert body["totals"]["flops"] == rep["totals"]["flops"]
        finally:
            ui.stop()
