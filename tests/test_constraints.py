"""Weight constraints (SURVEY.md D1 — the reference's
org.deeplearning4j.nn.conf.constraint package: MaxNorm/MinMaxNorm/
UnitNorm/NonNegative post-update projections, builder
constrainWeights/constrainBias/constrainAllParameters, and the Keras
kernel_constraint/bias_constraint import surface)."""
import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Sgd
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn.conf.builders import (
    MultiLayerConfiguration, NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.constraints import (
    LayerConstraint, MaxNormConstraint, MinMaxNormConstraint,
    NonNegativeConstraint, UnitNormConstraint, apply_constraints)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _unit_norms(w):
    return np.sqrt(np.sum(np.square(np.asarray(w, np.float32)),
                          axis=0))


class TestConstraintMath:
    def test_max_norm_rescales_only_violators(self):
        w = jnp.asarray([[3.0, 0.1], [4.0, 0.1]])   # norms 5, ~0.14
        out = np.asarray(MaxNormConstraint(2.0).apply(w))
        norms = _unit_norms(out)
        assert norms[0] == pytest.approx(2.0, rel=1e-5)
        # the compliant unit is untouched
        np.testing.assert_allclose(out[:, 1], [0.1, 0.1], atol=1e-6)

    def test_unit_norm_projects_to_sphere(self):
        w = jnp.asarray(np.random.RandomState(0).randn(6, 4) * 3)
        norms = _unit_norms(UnitNormConstraint().apply(w))
        np.testing.assert_allclose(norms, 1.0, atol=1e-5)

    def test_min_max_norm_both_sides(self):
        w = jnp.asarray([[5.0, 0.01], [0.0, 0.0]])  # norms 5, 0.01
        out = np.asarray(MinMaxNormConstraint(0.5, 2.0).apply(w))
        norms = _unit_norms(out)
        assert norms[0] == pytest.approx(2.0, rel=1e-4)
        assert norms[1] == pytest.approx(0.5, rel=1e-3)

    def test_min_max_norm_partial_rate(self):
        w = jnp.asarray([[4.0], [0.0]])             # norm 4
        out = np.asarray(MinMaxNormConstraint(0.0, 2.0, rate=0.5)
                         .apply(w))
        # half-way projection: 0.5 * (2/4) + 0.5 = 0.75 -> norm 3
        assert _unit_norms(out)[0] == pytest.approx(3.0, rel=1e-4)

    def test_non_negative_clamps(self):
        w = jnp.asarray([[-1.0, 2.0], [3.0, -4.0]])
        out = np.asarray(NonNegativeConstraint().apply(w))
        np.testing.assert_allclose(out, [[0.0, 2.0], [3.0, 0.0]])

    def test_bf16_dtype_preserved(self):
        w = jnp.asarray(np.random.RandomState(1).randn(4, 3),
                        jnp.bfloat16)
        for c in (MaxNormConstraint(1.0), UnitNormConstraint(),
                  MinMaxNormConstraint(0.1, 1.0),
                  NonNegativeConstraint()):
            assert c.apply(w).dtype == jnp.bfloat16

    def test_apply_constraints_param_routing(self):
        layer = DenseLayer(n_in=3, n_out=2)
        layer.constrain_weights = [NonNegativeConstraint()]
        layer.constrain_bias = [MaxNormConstraint(0.5)]
        params = {"W": jnp.asarray([[-1.0, 1.0]] * 3),
                  "b": jnp.asarray([3.0, 4.0])}      # norm 5
        out = apply_constraints(layer, params)
        assert np.asarray(out["W"]).min() >= 0.0
        assert np.linalg.norm(np.asarray(out["b"])) == \
            pytest.approx(0.5, rel=1e-4)


class TestConstrainedTraining:
    def _fit(self, constrained: bool, steps=30):
        b = NeuralNetConfiguration.Builder().seed(7) \
            .updater(Sgd(0.5))                       # big LR forces drift
        if constrained:
            b = b.constrain_weights(MaxNormConstraint(1.0))
        conf = b.list() \
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH)) \
            .layer(OutputLayer(n_in=16, n_out=4,
                               activation=Activation.SOFTMAX,
                               loss_function=LossFunction.MCXENT)) \
            .build()
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(3)
        x = rng.randn(32, 8).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 32)]
        for _ in range(steps):
            net.fit(x, y)
        return net

    def test_max_norm_bounds_training_while_free_net_drifts(self):
        free = self._fit(constrained=False)
        bound = self._fit(constrained=True)
        free_norms = np.concatenate([
            _unit_norms(free.params[k]["W"]) for k in free.params])
        bound_norms = np.concatenate([
            _unit_norms(bound.params[k]["W"]) for k in bound.params])
        assert free_norms.max() > 1.5          # SGD at lr .5 drifts
        assert bound_norms.max() <= 1.0 + 1e-3  # projection held
        # and the constrained net still learned (loss finite, moved)
        assert np.isfinite(bound.score())

    def test_per_layer_constraint_overrides_global(self):
        conf = NeuralNetConfiguration.Builder().seed(1) \
            .updater(Sgd(0.5)) \
            .constrain_weights(MaxNormConstraint(1.0)).list() \
            .layer(DenseLayer(n_in=4, n_out=8,
                              activation=Activation.RELU,
                              constrain_weights=[
                                  MaxNormConstraint(0.25)])) \
            .layer(OutputLayer(n_in=8, n_out=2,
                               activation=Activation.SOFTMAX,
                               loss_function=LossFunction.MCXENT)) \
            .build()
        assert conf.layers[0].constrain_weights == \
            [MaxNormConstraint(0.25)]
        assert conf.layers[1].constrain_weights == \
            [MaxNormConstraint(1.0)]
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(5)
        x = rng.randn(16, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
        for _ in range(20):
            net.fit(x, y)
        assert _unit_norms(net.params["layer_0"]["W"]).max() \
            <= 0.25 + 1e-3
        assert _unit_norms(net.params["layer_1"]["W"]).max() \
            <= 1.0 + 1e-3

    def test_fit_steps_applies_constraints(self):
        conf = NeuralNetConfiguration.Builder().seed(2) \
            .updater(Sgd(0.5)) \
            .constrain_all_parameters(NonNegativeConstraint()).list() \
            .layer(DenseLayer(n_in=6, n_out=6,
                              activation=Activation.SIGMOID)) \
            .layer(OutputLayer(n_in=6, n_out=3,
                               activation=Activation.SOFTMAX,
                               loss_function=LossFunction.MCXENT)) \
            .build()
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(9)

        class DS:
            features = rng.randn(16, 6).astype(np.float32)
            labels = np.eye(3, dtype=np.float32)[
                rng.randint(0, 3, 16)]

        net.fit_steps(DS(), 25)
        for k, tab in net.params.items():
            for name, p in tab.items():
                assert np.asarray(p).min() >= -1e-6, (k, name)

    def test_graph_training_constraint(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = NeuralNetConfiguration.Builder().seed(4) \
            .updater(Sgd(0.5)) \
            .constrain_weights(UnitNormConstraint()) \
            .graph_builder() \
            .add_inputs("in") \
            .add_layer("d", DenseLayer(n_in=5, n_out=10,
                                       activation=Activation.TANH),
                       "in") \
            .add_layer("out", OutputLayer(
                n_in=10, n_out=2, activation=Activation.SOFTMAX,
                loss_function=LossFunction.MCXENT), "d") \
            .set_outputs("out").build()
        g = ComputationGraph(conf).init()
        rng = np.random.RandomState(11)
        x = rng.randn(12, 5).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 12)]
        for _ in range(10):
            g.fit([x], [y])
        norms = _unit_norms(g.params["d"]["W"])
        np.testing.assert_allclose(norms, 1.0, atol=1e-3)


class TestConstraintSerde:
    def test_json_round_trip(self):
        conf = NeuralNetConfiguration.Builder() \
            .constrain_weights(MaxNormConstraint(1.5)) \
            .constrain_bias(NonNegativeConstraint()).list() \
            .layer(DenseLayer(
                n_in=3, n_out=4,
                constrain_all=[MinMaxNormConstraint(0.2, 2.0, 0.7)])) \
            .layer(OutputLayer(n_in=4, n_out=2,
                               loss_function=LossFunction.MSE)) \
            .build()
        back = MultiLayerConfiguration.from_json(conf.to_json())
        assert back.layers[0].constrain_all == \
            [MinMaxNormConstraint(0.2, 2.0, 0.7)]
        assert back.layers[0].constrain_weights == \
            [MaxNormConstraint(1.5)]
        assert back.layers[1].constrain_bias == \
            [NonNegativeConstraint()]

    def test_registry_round_trip_each(self):
        for c in (MaxNormConstraint(3.0, dims=(0, 1)),
                  MinMaxNormConstraint(0.1, 0.9, 0.5),
                  UnitNormConstraint(), NonNegativeConstraint()):
            assert LayerConstraint.from_map(c.to_map()) == c


class TestKerasConstraintImport:
    def test_kernel_and_bias_constraints_attach_and_bound(self, tmp_path):
        tf = pytest.importorskip("tensorflow")
        keras = tf.keras
        model = keras.Sequential([
            keras.layers.Input((6,)),
            keras.layers.Dense(8, activation="relu",
                               kernel_constraint=
                               keras.constraints.MaxNorm(1.0),
                               bias_constraint=
                               keras.constraints.NonNeg()),
            keras.layers.Dense(3, activation="softmax",
                               kernel_constraint=
                               keras.constraints.UnitNorm()),
        ])
        model.compile(loss="categorical_crossentropy")
        path = str(tmp_path / "model.keras")
        model.save(path)
        from deeplearning4j_tpu.modelimport.keras import KerasModelImport
        net = KerasModelImport \
            .import_keras_sequential_model_and_weights(path)
        # keras axis=0 (its default) translates verbatim to dims=(0,);
        # kernel_constraint scopes to the kernel param "W" exactly
        assert net.conf.layers[0].constrain_params == \
            {"W": [MaxNormConstraint(1.0, dims=(0,))]}
        assert net.conf.layers[0].constrain_bias == \
            [NonNegativeConstraint()]
        assert net.conf.layers[1].constrain_params == \
            {"W": [UnitNormConstraint(dims=(0,))]}
        # the imported constraints actually bite during training
        net.conf.updater = Sgd(0.5)
        rng = np.random.RandomState(0)
        x = rng.randn(16, 6).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 16)]
        for _ in range(15):
            net.fit(x, y)
        assert _unit_norms(net.params["layer_0"]["W"]).max() \
            <= 1.0 + 1e-3
        assert np.asarray(net.params["layer_0"]["b"]).min() >= -1e-6
        np.testing.assert_allclose(
            _unit_norms(net.params["layer_1"]["W"]), 1.0, atol=1e-3)

    def test_bidirectional_inner_constraint_imports(self, tmp_path):
        tf = pytest.importorskip("tensorflow")
        keras = tf.keras
        model = keras.Sequential([
            keras.layers.Input((5, 4)),
            keras.layers.Bidirectional(keras.layers.LSTM(
                6, return_sequences=True,
                kernel_constraint=keras.constraints.MaxNorm(0.5))),
            keras.layers.GlobalAveragePooling1D(),
            keras.layers.Dense(2, activation="softmax"),
        ])
        model.compile(loss="categorical_crossentropy")
        path = str(tmp_path / "model.keras")
        model.save(path)
        from deeplearning4j_tpu.modelimport.keras import KerasModelImport
        net = KerasModelImport \
            .import_keras_sequential_model_and_weights(path)
        # the INNER layer's kernel_constraint scopes to "W" only — the
        # recurrent kernel RW is NOT projected (keras semantics)
        assert net.conf.layers[0].constrain_params == \
            {"W": [MaxNormConstraint(0.5, dims=(0,))]}
        # the projection recurses into the fwd/bwd nested param tables
        # without crashing, and bounds both directions' weights
        net.conf.updater = Sgd(0.5)
        rng = np.random.RandomState(2)
        x = rng.randn(8, 5, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 8)]
        for _ in range(10):
            net.fit(x, y)
        tab = net.params["layer_0"]
        for d in ("fwd", "bwd"):
            per_unit = np.sqrt(np.sum(np.square(
                np.asarray(tab[d]["W"], np.float32)), axis=0))
            assert per_unit.max() <= 0.5 + 1e-3, d


class TestNestedParamTables:
    def test_global_constraint_with_bidirectional_native(self):
        """Repro from review: a GLOBAL constraint flows onto a
        Bidirectional layer whose param table nests fwd/bwd dicts —
        must project at the leaves, not crash on the dict."""
        from deeplearning4j_tpu.nn import InputType
        from deeplearning4j_tpu.nn.conf.layers_recurrent import (
            Bidirectional, LSTM)
        from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
        conf = NeuralNetConfiguration.Builder().seed(3) \
            .updater(Sgd(0.5)) \
            .constrain_weights(MaxNormConstraint(1.0)).list() \
            .layer(Bidirectional(fwd=LSTM(n_out=5))) \
            .layer(RnnOutputLayer(n_out=2,
                                  activation=Activation.SOFTMAX,
                                  loss_function=LossFunction.MCXENT)) \
            .set_input_type(InputType.recurrent(4)).build()
        net = MultiLayerNetwork(conf).init()
        rng = np.random.RandomState(7)
        x = rng.randn(6, 7, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, (6, 7))]
        for _ in range(8):
            net.fit(x, y)
        tab = net.params["layer_0"]
        for d in ("fwd", "bwd"):
            for name, p in tab[d].items():
                if np.ndim(p) >= 2:
                    per_unit = np.sqrt(np.sum(np.square(
                        np.asarray(p, np.float32)), axis=0))
                    assert per_unit.max() <= 1.0 + 1e-3, (d, name)

    def test_lstm_kernel_constraint_does_not_touch_recurrent(
            self, tmp_path):
        """keras per-param semantics: kernel_constraint projects the
        input kernel W only; RW must drift freely (code-review
        finding: an early draft conflated them)."""
        tf = pytest.importorskip("tensorflow")
        keras = tf.keras
        model = keras.Sequential([
            keras.layers.Input((6, 3)),
            keras.layers.LSTM(4, kernel_constraint=
                              keras.constraints.MaxNorm(0.3)),
            keras.layers.Dense(2, activation="softmax"),
        ])
        model.compile(loss="categorical_crossentropy")
        path = str(tmp_path / "m.keras")
        model.save(path)
        from deeplearning4j_tpu.modelimport.keras import KerasModelImport
        net = KerasModelImport \
            .import_keras_sequential_model_and_weights(path)
        assert net.conf.layers[0].constrain_params == \
            {"W": [MaxNormConstraint(0.3, dims=(0,))]}
        net.conf.updater = Sgd(0.5)
        rng = np.random.RandomState(4)
        x = rng.randn(16, 6, 3).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 16)]
        for _ in range(12):
            net.fit(x, y)
        w_norms = np.sqrt(np.sum(np.square(np.asarray(
            net.params["layer_0"]["W"], np.float32)), axis=0))
        rw_norms = np.sqrt(np.sum(np.square(np.asarray(
            net.params["layer_0"]["RW"], np.float32)), axis=0))
        assert w_norms.max() <= 0.3 + 1e-3
        assert rw_norms.max() > 0.3      # unconstrained: free to exceed

    def test_unknown_constraint_warns_unless_enforced(self, tmp_path):
        """Unsupported constraint classes skip with a warning on plain
        import (inference unaffected) and raise only under
        enforce_training_config — the reference's switch for
        training-only config it can't honor."""
        tf = pytest.importorskip("tensorflow")
        keras = tf.keras

        @keras.utils.register_keras_serializable("test_constraints")
        class Odd(keras.constraints.Constraint):
            def __call__(self, w):
                return w

            def get_config(self):
                return {}

        model = keras.Sequential([
            keras.layers.Input((4,)),
            keras.layers.Dense(3, kernel_constraint=Odd()),
        ])
        path = str(tmp_path / "m.keras")
        model.save(path)
        from deeplearning4j_tpu.modelimport.keras import (
            InvalidKerasConfigurationException, KerasModelImport)
        net = KerasModelImport \
            .import_keras_sequential_model_and_weights(path)
        assert not net.conf.layers[0].constrain_params
        x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        import jax
        with jax.default_matmul_precision("highest"):
            # f32 matmuls for the TF-parity check (TPU default is
            # bf16-accumulate — the algorithm-equivalence fixture)
            got = net.output(x)
        np.testing.assert_allclose(
            got, np.asarray(model(x)), atol=1e-4, rtol=1e-3)
        with pytest.raises(InvalidKerasConfigurationException):
            KerasModelImport.import_keras_sequential_model_and_weights(
                path, enforce_training_config=True)

    def test_json_round_trip_constrain_params(self):
        layer = DenseLayer(n_in=3, n_out=4, constrain_params={
            "W": [MaxNormConstraint(0.7, dims=(0,))]})
        from deeplearning4j_tpu.nn.conf.layers import Layer
        back = Layer.from_map(layer.to_map())
        assert back.constrain_params == \
            {"W": [MaxNormConstraint(0.7, dims=(0,))]}
