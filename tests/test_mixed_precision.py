"""Mixed precision (compute_dtype='bfloat16'): bf16 math, f32 master
params — SURVEY.md §7 design stance ("bfloat16 on the MXU")."""
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration
from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization,
                                               ConvolutionLayer,
                                               DenseLayer, OutputLayer)


def _conf(compute_dtype=None):
    return (NeuralNetConfiguration.Builder().seed(7).updater(Adam(2e-2))
            .compute_data_type(compute_dtype)
            .list()
            .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=8))
            .layer(BatchNormalization(activation=Activation.RELU))
            .layer(DenseLayer(n_out=16, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3,
                               activation=Activation.SOFTMAX,
                               loss_function=LossFunction.MCXENT))
            .set_input_type(InputType.convolutional(8, 8, 1))
            .build())


def _data(n=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8, 8, 1).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return DataSet(x, y)


class TestMixedPrecision:
    def test_master_params_stay_f32_and_loss_decreases(self):
        net = MultiLayerNetwork(_conf("bfloat16")).init()
        ds = _data()
        losses = []
        for _ in range(15):
            net.fit(ds)
            losses.append(float(net.score()))
        for leaf in [v for d in net.params.values() for v in d.values()]:
            assert leaf.dtype == jnp.float32, leaf.dtype
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] - 0.1, losses

    def test_output_is_f32(self):
        net = MultiLayerNetwork(_conf("bfloat16")).init()
        out = net.output(np.asarray(_data(4).features))
        assert np.asarray(out).dtype == np.float32

    def test_tracks_f32_training_closely(self):
        """bf16 and f32 runs agree on early-training loss to bf16
        tolerance (same seed, same data)."""
        ds = _data(64, seed=1)
        runs = {}
        for cd in (None, "bfloat16"):
            net = MultiLayerNetwork(_conf(cd)).init()
            for _ in range(5):
                net.fit(ds)
            runs[cd] = float(net.score())
        assert abs(runs[None] - runs["bfloat16"]) < 0.15, runs

    def test_json_roundtrip_keeps_compute_dtype(self):
        conf = _conf("bfloat16")
        again = MultiLayerConfiguration.from_json(conf.to_json())
        assert again.compute_dtype == "bfloat16"
        assert MultiLayerConfiguration.from_json(
            _conf(None).to_json()).compute_dtype is None

    def test_device_resident_dataset_not_copied_to_host(self):
        import jax
        x = jax.device_put(jnp.zeros((4, 8, 8, 1), jnp.float32))
        y = jax.device_put(jnp.eye(3, dtype=jnp.float32)[
            jnp.asarray([0, 1, 2, 0])])
        ds = DataSet(x, y)
        assert ds.features is x       # no host round-trip
        assert ds.labels is y
