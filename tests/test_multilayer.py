"""MultiLayerNetwork tests: config building, shape inference, JSON
round-trip, training convergence (reference test style: GradientCheckTests /
MultiLayerTest equivalents, SURVEY.md section 4.5/4.8)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam, Sgd
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerConfiguration,
                                   MultiLayerNetwork, NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.builders import GradientNormalization
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
    DropoutLayer, EmbeddingLayer, GlobalPoolingLayer, OutputLayer,
    PoolingType, SubsamplingLayer)
from deeplearning4j_tpu.nn.weights import WeightInit


def _mlp_conf(updater=None):
    return (NeuralNetConfiguration.Builder()
            .seed(42)
            .updater(updater or Adam(1e-2))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=32, activation=Activation.RELU))
            .layer(DenseLayer(n_out=32, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4))
            .build())


def _toy_classification(n=256, seed=0):
    """3-class linearly-separable-ish blobs, 4 features."""
    rng = np.random.RandomState(seed)
    centers = np.array([[2, 0, 0, 0], [0, 2, 0, 0], [0, 0, 2, 0]],
                       dtype=np.float32)
    ys = rng.randint(0, 3, size=n)
    xs = centers[ys] + 0.3 * rng.randn(n, 4).astype(np.float32)
    labels = np.eye(3, dtype=np.float32)[ys]
    return xs, labels, ys


class TestConfig:
    def test_shape_inference(self):
        conf = _mlp_conf()
        assert conf.layers[0].n_in == 4
        assert conf.layers[1].n_in == 32
        assert conf.layers[2].n_in == 32

    def test_json_round_trip(self):
        conf = _mlp_conf()
        js = conf.to_json()
        back = MultiLayerConfiguration.from_json(js)
        assert len(back.layers) == 3
        assert back.layers[0].n_out == 32
        assert back.layers[2].loss_function == LossFunction.MCXENT
        assert back.updater == conf.updater
        assert back.to_json() == js

    def test_cnn_shape_inference_and_preprocessors(self):
        conf = (NeuralNetConfiguration.Builder()
                .updater(Sgd(0.1))
                .list()
                .layer(ConvolutionLayer(kernel_size=(5, 5), n_out=8,
                                        stride=(1, 1)))
                .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                        kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=16, activation=Activation.RELU))
                .layer(OutputLayer(n_out=10))
                .set_input_type(InputType.convolutional_flat(28, 28, 1))
                .build())
        # flat input -> conv needs FF->CNN preprocessor at 0
        assert 0 in conf.input_preprocessors
        # pool output (12x12x8) -> dense needs CNN->FF at 2
        assert 2 in conf.input_preprocessors
        assert conf.layers[0].n_in == 1
        assert conf.layers[2].n_in == 12 * 12 * 8

    def test_builder_parity_chain(self):
        # reference-style fluent Layer.Builder chains
        layer = (DenseLayer.Builder()
                 .n_out(64)
                 .activation(Activation.TANH)
                 .build())
        assert layer.n_out == 64
        assert layer.activation is Activation.TANH
        conv = ConvolutionLayer.Builder(5, 5).n_out(20).build()
        assert conv.kernel_size == (5, 5)


class TestTraining:
    def test_mlp_converges(self):
        xs, labels, ys = _toy_classification()
        net = MultiLayerNetwork(_mlp_conf()).init()
        loss0 = None
        for epoch in range(30):
            net.fit(xs, labels)
            if loss0 is None:
                loss0 = net.score()
        assert net.score() < 0.3 * loss0
        preds = net.predict(xs)
        acc = float(np.mean(preds == ys))
        assert acc > 0.9

    def test_output_probabilities(self):
        xs, labels, _ = _toy_classification(32)
        net = MultiLayerNetwork(_mlp_conf()).init()
        out = net.output(xs)
        assert out.shape == (32, 3)
        np.testing.assert_allclose(np.asarray(jnp.sum(out, -1)),
                                   np.ones(32), rtol=1e-5)

    def test_score_decreases_with_sgd_and_gradient_clipping(self):
        xs, labels, _ = _toy_classification()
        conf = (NeuralNetConfiguration.Builder()
                .seed(1)
                .updater(Sgd(0.5))
                .gradient_normalization(
                    GradientNormalization.CLIP_L2_PER_LAYER)
                .gradient_normalization_threshold(1.0)
                .list()
                .layer(DenseLayer(n_out=16, activation=Activation.TANH))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        for _ in range(20):
            net.fit(xs, labels)
        ds = type("DS", (), {"features": xs, "labels": labels})()
        assert net.score(ds) < 1.0

    def test_l2_regularization_included_in_score(self):
        xs, labels, _ = _toy_classification(16)
        conf_reg = (NeuralNetConfiguration.Builder().seed(3)
                    .updater(Sgd(0.0)).l2(10.0).list()
                    .layer(DenseLayer(n_out=8))
                    .layer(OutputLayer(n_out=3))
                    .set_input_type(InputType.feed_forward(4)).build())
        conf_no = (NeuralNetConfiguration.Builder().seed(3)
                   .updater(Sgd(0.0)).list()
                   .layer(DenseLayer(n_out=8))
                   .layer(OutputLayer(n_out=3))
                   .set_input_type(InputType.feed_forward(4)).build())
        ds = type("DS", (), {"features": xs, "labels": labels})()
        s_reg = MultiLayerNetwork(conf_reg).init().score(ds)
        s_no = MultiLayerNetwork(conf_no).init().score(ds)
        assert s_reg > s_no + 0.1

    def test_batchnorm_state_updates(self):
        xs = np.random.RandomState(0).randn(64, 4).astype(np.float32) * 5
        labels = np.eye(3, dtype=np.float32)[
            np.random.RandomState(1).randint(0, 3, 64)]
        conf = (NeuralNetConfiguration.Builder().seed(0)
                .updater(Sgd(0.01)).list()
                .layer(DenseLayer(n_out=8))
                .layer(BatchNormalization())
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        before = np.asarray(net.states["layer_1"]["mean"]).copy()
        net.fit(xs, labels)
        after = np.asarray(net.states["layer_1"]["mean"])
        assert not np.allclose(before, after)

    def test_dropout_only_in_training(self):
        xs = np.ones((8, 4), dtype=np.float32)
        conf = (NeuralNetConfiguration.Builder().seed(0)
                .updater(Sgd(0.1)).list()
                .layer(DenseLayer(n_out=16, dropout=0.5))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        o1 = np.asarray(net.output(xs))
        o2 = np.asarray(net.output(xs))
        np.testing.assert_allclose(o1, o2)  # inference is deterministic

    def test_embedding_global_pooling(self):
        # tiny bag-of-tokens classifier
        rng = np.random.RandomState(0)
        tokens = rng.randint(0, 20, size=(32, 6)).astype(np.int32)
        labels = np.eye(2, dtype=np.float32)[(tokens.sum(-1) % 2)]
        conf = (NeuralNetConfiguration.Builder().seed(0)
                .updater(Adam(1e-2)).list()
                .layer(EmbeddingLayer(n_in=20, n_out=8))
                .layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
                .layer(OutputLayer(n_in=8, n_out=2))
                .build())
        net = MultiLayerNetwork(conf).init()
        out = net.output(tokens)
        assert out.shape == (32, 2)

    def test_param_table_and_clone(self):
        net = MultiLayerNetwork(_mlp_conf()).init()
        table = net.param_table()
        assert "0_W" in table and "0_b" in table and "2_W" in table
        assert net.num_params() == sum(int(np.prod(v.shape))
                                       for k, v in table.items()
                                       if not k.endswith(("mean", "var")))
        c = net.clone()
        xs = np.ones((2, 4), dtype=np.float32)
        np.testing.assert_allclose(np.asarray(net.output(xs)),
                                   np.asarray(c.output(xs)))

    def test_summary(self):
        net = MultiLayerNetwork(_mlp_conf()).init()
        s = net.summary()
        assert "Total params" in s


class TestCnnTraining:
    def test_small_cnn_trains(self):
        rng = np.random.RandomState(0)
        xs = rng.randn(64, 8 * 8).astype(np.float32)
        ys = (xs.reshape(64, 8, 8).mean((1, 2)) > 0).astype(int)
        labels = np.eye(2, dtype=np.float32)[ys]
        conf = (NeuralNetConfiguration.Builder().seed(0)
                .updater(Adam(1e-2)).list()
                .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=4,
                                        activation=Activation.RELU))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=16, activation=Activation.RELU))
                .layer(OutputLayer(n_out=2))
                .set_input_type(InputType.convolutional_flat(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        for _ in range(30):
            net.fit(xs, labels)
        acc = float(np.mean(net.predict(xs) == ys))
        assert acc > 0.85


class TestGradients:
    def test_analytic_vs_numeric_gradient(self):
        """Reference GradientCheckUtil pattern (SURVEY.md section 4.5):
        central-difference check in float64."""
        jax.config.update("jax_enable_x64", True)
        try:
            xs = np.random.RandomState(0).randn(4, 3)
            labels = np.eye(2)[np.random.RandomState(1).randint(0, 2, 4)]
            conf = (NeuralNetConfiguration.Builder().seed(0)
                    .updater(Sgd(0.1)).data_type("float64").list()
                    .layer(DenseLayer(n_out=5, activation=Activation.TANH))
                    .layer(OutputLayer(n_out=2))
                    .set_input_type(InputType.feed_forward(3)).build())
            net = MultiLayerNetwork(conf).init()
            out_layer = net.output_layer_conf

            def loss(params):
                out, _ = net._forward(params, net.states,
                                      jnp.asarray(xs), training=False,
                                      rng=None, want_logits=True)
                return out_layer.compute_loss(jnp.asarray(labels), out,
                                              from_logits=True)

            analytic = jax.grad(loss)(net.params)
            eps = 1e-6
            for lk in ("layer_0", "layer_1"):
                W = net.params[lk]["W"]
                flatW = np.asarray(W).ravel()
                for idx in [0, flatW.size // 2, flatW.size - 1]:
                    delta = np.zeros_like(flatW)
                    delta[idx] = eps
                    d = delta.reshape(W.shape)
                    p_plus = dict(net.params)
                    p_plus[lk] = dict(net.params[lk], W=W + d)
                    p_minus = dict(net.params)
                    p_minus[lk] = dict(net.params[lk], W=W - d)
                    num = (float(loss(p_plus)) - float(loss(p_minus))) / \
                        (2 * eps)
                    ana = float(np.asarray(analytic[lk]["W"]).ravel()[idx])
                    assert abs(num - ana) < 1e-5, (lk, idx, num, ana)
        finally:
            jax.config.update("jax_enable_x64", False)
