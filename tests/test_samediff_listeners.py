"""SameDiff training listeners + evaluation-during-training (SURVEY.md
S4/S8 — the reference's SameDiff.fit(iter, epochs, listeners...) with
ListenerList and History evaluation records; r4 verdict Missing #2:
the imported-model path used to train blind while MLN/graph had the
full listener bus)."""
import os
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.autodiff.training import TrainingConfig
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.evaluation import Evaluation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.optimize.listeners import (
    CollectScoresListener, ScoreIterationListener)
from deeplearning4j_tpu.utils.checkpoint import CheckpointListener

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _classifier_sd():
    """Tiny softmax classifier with placeholders x [B,4] / y [B,3]."""
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 4))
    y = sd.placeholder("y", shape=(None, 3))
    w = sd.var("w", array=np.zeros((4, 3), np.float32))
    b = sd.var("b", array=np.zeros((3,), np.float32))
    logits = (x @ w + b).rename("logits")
    sd.nn.softmax(logits, name="probs")
    sd.loss.softmax_cross_entropy(y, logits, name="loss")
    sd.set_loss_variables("loss")
    sd.set_training_config(
        TrainingConfig.Builder().updater(Adam(0.1))
        .data_set_feature_mapping("x")
        .data_set_label_mapping("y").build())
    return sd


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    labels = (x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)
    y = np.eye(3, dtype=np.float32)[labels]
    return x, y


class TestListenerBus:
    def test_score_and_collect_listeners_fire(self):
        sd = _classifier_sd()
        collect = CollectScoresListener()
        sd.set_listeners(ScoreIterationListener(5), collect)
        x, y = _data()
        it = ListDataSetIterator([DataSet(x, y)] * 4)
        sd.fit(it, n_epochs=3)
        # 12 iterations, every one collected, scores finite + falling
        assert len(collect.scores) == 12
        its = [i for i, _ in collect.scores]
        assert its == list(range(12))
        scores = [s for _, s in collect.scores]
        assert np.isfinite(scores).all()
        assert scores[-1] < scores[0]
        assert sd.epoch_count == 3

    def test_per_call_listeners_compose_with_set_listeners(self):
        sd = _classifier_sd()
        base = CollectScoresListener()
        extra = CollectScoresListener()
        sd.set_listeners(base)
        x, y = _data()
        it = ListDataSetIterator([DataSet(x, y)] * 2)
        sd.fit(it, n_epochs=1, listeners=[extra])
        assert len(base.scores) == len(extra.scores) == 2

    def test_fit_steps_fires_listener_group(self):
        sd = _classifier_sd()
        collect = CollectScoresListener()
        sd.set_listeners(collect)
        x, y = _data()
        sd.fit_steps({"x": x, "y": y}, 7)
        assert len(collect.scores) == 1
        assert collect.scores[0][0] == 6     # final iteration index
        assert np.isfinite(collect.scores[0][1])
        assert sd.last_batch_size == 64


class TestEvaluationDuringTraining:
    def test_history_gains_evaluation_records(self):
        sd = _classifier_sd()
        x, y = _data()
        xv, yv = _data(n=32, seed=1)
        it = ListDataSetIterator([DataSet(x, y)] * 4)
        val = ListDataSetIterator([DataSet(xv, yv)])
        hist = sd.fit(it, n_epochs=4, validation_iter=val,
                      validation_evaluations={"probs": Evaluation})
        assert len(hist.epoch_evaluations) == 4
        evals = hist.evaluations("probs")
        assert len(evals) == 4
        # the task is learnable: final accuracy beats the first epoch's
        assert evals[-1].accuracy() >= evals[0].accuracy()
        assert evals[-1].accuracy() > 0.5
        assert np.isfinite(hist.validation_loss_curve()).all()
        assert hist.validation_losses[-1] < hist.validation_losses[0]
        assert hist.final_evaluation("probs") is evals[-1]

    def test_validation_frequency_skips_epochs(self):
        sd = _classifier_sd()
        x, y = _data()
        it = ListDataSetIterator([DataSet(x, y)])
        val = ListDataSetIterator([DataSet(x, y)])
        hist = sd.fit(it, n_epochs=4, validation_iter=val,
                      validation_evaluations={"probs": Evaluation},
                      validation_frequency=2)
        assert len(hist.evaluations("probs")) == 2
        assert np.isnan(hist.validation_losses[0])
        assert np.isfinite(hist.validation_losses[1])


class TestCheckpointListenerOnSameDiff:
    def test_async_epoch_checkpoints_and_resume(self, tmp_path):
        sd = _classifier_sd()
        ckpt = CheckpointListener(tmp_path, save_every_n_epochs=2,
                                  asynchronous=True)
        sd.set_listeners(ckpt)
        x, y = _data()
        it = ListDataSetIterator([DataSet(x, y)] * 2)
        sd.fit(it, n_epochs=4)
        ckpt.flush()
        saved = sorted(tmp_path.glob("checkpoint_*.zip"))
        assert len(saved) == 2                   # epochs 2 and 4
        back = SameDiff.load(str(saved[-1]))
        np.testing.assert_allclose(
            np.asarray(back.get_variable("w").get_arr()),
            np.asarray(sd.get_variable("w").get_arr()),
            rtol=1e-6, atol=1e-7)
        # resumable: updater iteration persisted through the zip
        assert back.iteration_count == 8
        back.fit(it, n_epochs=1)                 # trains on, no error
        assert back.iteration_count == 10

    def test_load_checkpoint_dispatches_samediff_zip(self, tmp_path):
        """Regression (ADVICE.md r5): ``CheckpointListener.
        load_checkpoint`` — the FaultTolerantTrainer resume entry —
        must dispatch SameDiff-format zips written by
        ``checkpoint_snapshot()`` through the format-sniffing
        ``ModelSerializer.restore_model``, not fall through
        ``restore_multi_layer_network`` (which would die on the
        missing MLN config entry)."""
        sd = _classifier_sd()
        ckpt = CheckpointListener(tmp_path, save_every_n_iterations=1)
        sd.set_listeners(ckpt)
        x, y = _data()
        sd.fit_steps({"x": x, "y": y}, 3)
        ckpt.flush()
        assert sorted(tmp_path.glob("checkpoint_*.zip"))
        back = CheckpointListener.load_checkpoint(tmp_path)
        assert isinstance(back, SameDiff)
        assert back.iteration_count == 3
        np.testing.assert_allclose(
            np.asarray(back.get_variable("w").get_arr()),
            np.asarray(sd.get_variable("w").get_arr()),
            rtol=1e-6, atol=1e-7)
        # a direct file path dispatches identically
        last = CheckpointListener.last_checkpoint_in(tmp_path)
        back2 = CheckpointListener.load_checkpoint(last)
        assert isinstance(back2, SameDiff)
        # and the restored program keeps training
        back.fit_steps({"x": x, "y": y}, 2)
        assert back.iteration_count == 5

    def test_iteration_checkpoints_via_fit_steps(self, tmp_path):
        """The benchmark-grade fori loop checkpoints too: one listener
        round per group, so save_every_n_iterations=1 saves after each
        fit_steps call (BASELINE #4's imported-model training loop)."""
        sd = _classifier_sd()
        ckpt = CheckpointListener(tmp_path, save_every_n_iterations=1,
                                  asynchronous=True)
        sd.set_listeners(ckpt)
        x, y = _data()
        sd.fit_steps({"x": x, "y": y}, 5)
        sd.fit_steps({"x": x, "y": y}, 5)
        ckpt.flush()
        saved = sorted(tmp_path.glob("checkpoint_*.zip"))
        assert len(saved) == 2
        back = SameDiff.load(str(saved[-1]))
        assert back.iteration_count == 10


class TestImportedModelParity:
    """The r4 verdict's acceptance shape: a TF-IMPORTED model trains
    with a score listener, periodic async checkpoints, and per-epoch
    eval — the full MLN listener experience on the S6 path (toy dims;
    real-dim training is test_tf_import_bert_base)."""

    def test_imported_bert_trains_with_listeners_and_checkpoints(
            self, tmp_path):
        pytest.importorskip("tensorflow")
        from benchmarks.tf_bert_builder import (build_frozen_bert,
                                                import_and_attach_mlm)
        vocab, hidden, heads, layers, seq, batch = 50, 16, 2, 2, 16, 4
        gd, _ = build_frozen_bert(seq, batch, vocab=vocab,
                                  hidden=hidden, heads=heads,
                                  layers=layers, intermediate=32)
        sd, loss_name = import_and_attach_mlm(
            gd, batch, seq, vocab=vocab, hidden=hidden,
            updater=Adam(1e-3))
        rs = np.random.RandomState(0)
        ids = rs.randint(0, vocab, (batch, seq)).astype(np.int32)
        seg = np.zeros((batch, seq), np.int32)
        mask = np.ones((batch, seq), np.int32)
        labels = np.where(rs.rand(batch, seq) < 0.15,
                          rs.randint(0, vocab, (batch, seq)),
                          -1).astype(np.int32)
        b = {"ids": ids, "seg": seg, "mask": mask,
             "mlm_labels": labels}
        collect = CollectScoresListener()
        ckpt = CheckpointListener(tmp_path, save_every_n_epochs=1,
                                  asynchronous=True)
        hist = sd.fit([b] * 3, n_epochs=2,
                      placeholders_fn=lambda bb: bb,
                      listeners=[collect, ckpt])
        ckpt.flush()
        assert len(collect.scores) == 6
        scores = [s for _, s in collect.scores]
        assert np.isfinite(scores).all() and scores[-1] < scores[0]
        saved = sorted(tmp_path.glob("checkpoint_*.zip"))
        assert len(saved) == 2
        back = SameDiff.load(str(saved[-1]))
        assert back.iteration_count == 6
        assert len(hist) == 2


def test_validation_with_dict_batches_via_label_mapping():
    """placeholders_fn-style dict batches validate too: labels come
    from the label-mapped placeholder, not a .labels attribute
    (code-review regression)."""
    sd = _classifier_sd()
    x, y = _data()
    hist = sd.fit([{"x": x, "y": y}] * 2, n_epochs=2,
                  placeholders_fn=lambda b: b,
                  validation_iter=[{"x": x, "y": y}],
                  validation_evaluations={"probs": Evaluation})
    assert len(hist.evaluations("probs")) == 2
    assert hist.evaluations("probs")[-1].accuracy() > 0.5
