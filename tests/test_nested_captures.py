"""Nested-subgraph captures are LIVE (round-2 verdict Weak #5 / ask
#6): a VARIABLE captured by a subgraph nested two or more levels deep
(cond-in-cond, while-in-cond) must receive gradients and train, not
freeze into the closure as a stale constant."""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu.autodiff.samediff import SameDiff


def _numeric_grad(f, w, eps=1e-3):
    g = np.zeros_like(w)
    for i in range(w.size):
        wp = w.copy(); wp[i] += eps
        wm = w.copy(); wm[i] -= eps
        g[i] = (f(wp) - f(wm)) / (2 * eps)
    return g


class TestNestedCaptures:
    def test_nested_cond_captured_variable_gradient(self):
        """loss = sum(cond(outer, cond(inner, b*w, b+w), a*0.5)):
        w is captured by the INNER cond's branches (two levels below
        the graph that owns it)."""
        w0 = np.float32([1.5, -0.5, 2.0])
        xv = np.float32([1.0, 2.0, 3.0])

        def build():
            sd = SameDiff()
            x = sd.placeholder("x", (3,))
            w = sd.var("w", array=w0.copy())
            pred = sd.math.gt(sd.math.reduce_sum(x),
                                   sd.constant("c0", np.float32(0.0)))

            def outer_true(a):
                csd = a.sd
                p2 = csd.math.gt(
                    sd.math.reduce_sum(w),    # also captured here
                    csd._as_var(np.float32(10.0)))

                def inner_true(b):
                    return b * w              # nested capture of w

                def inner_false(b):
                    return b + w              # nested capture of w

                y = csd.cond(p2, inner_true, inner_false, [a])
                return y

            def outer_false(a):
                return a * 0.5

            y = sd.cond(pred, outer_true, outer_false, [x])
            loss = sd.math.reduce_sum(y, name="loss")
            sd.set_loss_variables(["loss"])
            return sd

        sd = build()
        # forward: sum(w) = 3 < 10 → inner_false → x + w
        out = sd.output({"x": xv}, ["loss"])["loss"]
        assert float(out) == pytest.approx(float((xv + w0).sum()),
                                           rel=1e-6)
        got = sd.calculate_gradients({"x": xv}, ["w"])["w"]

        def ref(w):
            if xv.sum() <= 0:
                return (xv * 0.5).sum()
            if w.sum() > 10:
                return (xv * w).sum()
            return (xv + w).sum()

        want = _numeric_grad(ref, w0.astype(np.float64))
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-3)

    def test_nested_cond_captured_variable_trains(self):
        """fit() through the nested capture must move w (the frozen
        form trained it as a stale constant: zero gradient)."""
        from deeplearning4j_tpu.autodiff.training import TrainingConfig
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import \
            ListDataSetIterator
        from deeplearning4j_tpu.learning import Sgd

        w0 = np.float32([2.0, 2.0])
        sd = SameDiff()
        x = sd.placeholder("x", (2,))
        w = sd.var("w", array=w0.copy())
        pred = sd.math.gt(sd.math.reduce_sum(x),
                               sd.constant("c0", np.float32(0.0)))

        def outer_true(a):
            csd = a.sd
            p2 = csd.math.gt(csd.math.reduce_sum(a),
                                  csd._as_var(np.float32(100.0)))

            def inner_true(b):
                return b + w

            def inner_false(b):
                return b * w          # taken: loss = sum(x*w)

            y = csd.cond(p2, inner_true, inner_false, [a])
            return y

        y = sd.cond(pred, outer_true, lambda a: a, [x])
        sd.math.reduce_sum(y, name="loss")
        sd.set_loss_variables(["loss"])
        sd.set_training_config(
            TrainingConfig.Builder().updater(Sgd(0.1))
            .data_set_feature_mapping("x").build())
        xv = np.float32([[1.0, 3.0]])[0]
        it = ListDataSetIterator([DataSet(xv, None)])
        sd.fit(it, n_epochs=1)
        got = np.asarray(sd.get_variable("w").get_arr())
        # d loss / d w = x → w' = w - 0.1 * x
        np.testing.assert_allclose(got, w0 - 0.1 * xv, rtol=1e-5)

    def test_while_in_cond_captured_variable_gradient(self):
        """Bounded while INSIDE a cond branch, its body capturing w:
        gradients flow through both nesting levels."""
        w0 = np.float32(1.2)
        sd = SameDiff()
        x = sd.placeholder("x", ())
        w = sd.var("w", array=np.float32(w0))
        pred = sd.math.gt(x, sd.constant("c0", np.float32(0.0)))

        def true_fn(a):
            csd = a.sd
            i0 = csd._as_var(np.int32(0))

            def cond_fn(i, acc):
                return i.sd.math.lt(i, i.sd._as_var(np.int32(3)))

            def body_fn(i, acc):
                bsd = i.sd
                return (bsd.math.add(i, bsd._as_var(np.int32(1))),
                        acc * w)          # nested capture

            outs = csd.while_loop([i0, a], cond_fn, body_fn,
                                  max_iterations=4)
            return outs[1]

        y = sd.cond(pred, true_fn, lambda a: a, [x])
        sd.math.mul(y, sd.constant("one", np.float32(1.0)),
                    name="loss")
        sd.set_loss_variables(["loss"])
        xv = np.float32(2.0)
        out = sd.output({"x": xv}, ["loss"])["loss"]
        assert float(out) == pytest.approx(2.0 * w0 ** 3, rel=1e-5)
        got = float(np.asarray(
            sd.calculate_gradients({"x": xv}, ["w"])["w"]))
        # d/dw (x * w^3) = 3 x w^2
        assert got == pytest.approx(3 * 2.0 * w0 ** 2, rel=1e-4)
