"""Attention layer tests (reference: dl4j AttentionLayerTest — the
SelfAttention/LearnedSelfAttention/RecurrentAttention gradient-check
suite, SURVEY.md D4 "attention")."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import (GlobalPoolingLayer,
                                               OutputLayer, PoolingType,
                                               RnnOutputLayer)
from deeplearning4j_tpu.nn.conf.layers_attention import (
    LearnedSelfAttentionLayer, RecurrentAttentionLayer, SelfAttentionLayer,
    dot_product_attention, multi_head_attention)
from deeplearning4j_tpu.nn.conf.inputs import InputTypeRecurrent


def _seq_cls_data(n=64, t=12, f=8, seed=0):
    """Class = whether feature-0 mean over time is positive."""
    rng = np.random.RandomState(seed)
    x = rng.randn(n, t, f).astype(np.float32)
    y_idx = (x[:, :, 0].mean(1) > 0).astype(int)
    y = np.eye(2, dtype=np.float32)[y_idx]
    return x, y


def _attn_net(attn_layer, f=8):
    conf = (NeuralNetConfiguration.Builder()
            .seed(7).updater(Adam(5e-3)).list()
            .layer(attn_layer)
            .layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
            .layer(OutputLayer(n_out=2,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.recurrent(f))
            .build())
    return MultiLayerNetwork(conf).init()


class TestDotProductAttention:
    def test_matches_manual_softmax(self):
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(2, 4, 8), jnp.float32)
        k = jnp.asarray(rng.randn(2, 6, 8), jnp.float32)
        v = jnp.asarray(rng.randn(2, 6, 8), jnp.float32)
        out = dot_product_attention(q, k, v)
        s = np.einsum("bqd,bkd->bqk", q, k) / np.sqrt(8)
        w = np.exp(s - s.max(-1, keepdims=True))
        w /= w.sum(-1, keepdims=True)
        ref = np.einsum("bqk,bkd->bqd", w, v)
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)

    def test_key_mask_excludes_timesteps(self):
        """Changing a masked key/value must not change the output."""
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(1, 3, 4), jnp.float32)
        kv = rng.randn(1, 5, 4).astype(np.float32)
        mask = jnp.asarray([[1, 1, 1, 0, 0]], jnp.float32)[:, None, :]
        out1 = dot_product_attention(q, jnp.asarray(kv), jnp.asarray(kv),
                                     mask)
        kv2 = kv.copy()
        kv2[:, 3:] = 99.0
        out2 = dot_product_attention(q, jnp.asarray(kv2),
                                     jnp.asarray(kv2), mask)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                                   atol=1e-6)


class TestAttentionLayers:
    @pytest.mark.parametrize("layer,factor", [
        (SelfAttentionLayer(n_out=16, n_heads=2), 0.5),
        # unprojected: no attention params, only the head learns
        (SelfAttentionLayer(n_heads=1, project_input=False), 0.85),
        (LearnedSelfAttentionLayer(n_out=16, n_heads=2, n_queries=4), 0.5),
        (RecurrentAttentionLayer(n_out=16, n_heads=2), 0.5),
    ])
    def test_learns_sequence_classification(self, layer, factor):
        x, y = _seq_cls_data()
        net = _attn_net(layer)
        first = None
        for i in range(80):
            net.fit(x, y)
            if first is None:
                first = net.score()
        assert net.score() < first * factor, \
            f"{type(layer).__name__}: {first} -> {net.score()}"

    def test_self_attention_output_shape(self):
        x, _ = _seq_cls_data(n=4, t=10)
        lay = SelfAttentionLayer(n_in=8, n_out=16, n_heads=4)
        p = lay.init_params(jax.random.PRNGKey(0), InputTypeRecurrent(8))
        y, _ = lay.forward(p, jnp.asarray(x), training=False)
        assert y.shape == (4, 10, 16)

    def test_learned_queries_fixed_output_length(self):
        lay = LearnedSelfAttentionLayer(n_in=8, n_out=16, n_heads=2,
                                        n_queries=3)
        p = lay.init_params(jax.random.PRNGKey(0), InputTypeRecurrent(8))
        for t in (5, 9):
            x = jnp.zeros((2, t, 8))
            y, _ = lay.forward(p, x, training=False)
            assert y.shape == (2, 3, 16)
        ot = lay.get_output_type(InputTypeRecurrent(8, 9))
        assert ot.timesteps == 3 and ot.size == 16

    def test_recurrent_attention_is_stateful_sequence_map(self):
        lay = RecurrentAttentionLayer(n_in=8, n_out=16, n_heads=2)
        p = lay.init_params(jax.random.PRNGKey(0), InputTypeRecurrent(8))
        x = jnp.asarray(np.random.RandomState(0)
                        .randn(2, 7, 8), jnp.float32)
        y, st = lay.forward(p, x, training=False)
        assert y.shape == (2, 7, 16)
        np.testing.assert_allclose(np.asarray(st["h"]),
                                   np.asarray(y[:, -1]), atol=1e-6)

    def test_mask_isolates_padded_steps(self):
        """Output at unmasked steps is unchanged by padded-step values."""
        for lay in (SelfAttentionLayer(n_in=8, n_out=8, n_heads=2),
                    RecurrentAttentionLayer(n_in=8, n_out=8, n_heads=2)):
            p = lay.init_params(jax.random.PRNGKey(0),
                                InputTypeRecurrent(8))
            rng = np.random.RandomState(3)
            x = rng.randn(2, 6, 8).astype(np.float32)
            mask = jnp.asarray(np.array([[1, 1, 1, 1, 0, 0],
                                         [1, 1, 0, 0, 0, 0]],
                                        np.float32))
            y1, _ = lay.forward(p, jnp.asarray(x), training=False,
                                mask=mask)
            x2 = x.copy()
            x2[0, 4:] = 7.0
            x2[1, 2:] = -3.0
            y2, _ = lay.forward(p, jnp.asarray(x2), training=False,
                                mask=mask)
            np.testing.assert_allclose(np.asarray(y1[0, :4]),
                                       np.asarray(y2[0, :4]), atol=1e-5)
            np.testing.assert_allclose(np.asarray(y1[1, :2]),
                                       np.asarray(y2[1, :2]), atol=1e-5)

    def test_json_round_trip(self):
        from deeplearning4j_tpu.nn.conf.layers import Layer
        for lay in (SelfAttentionLayer(n_in=8, n_out=16, n_heads=2),
                    LearnedSelfAttentionLayer(n_in=8, n_out=16,
                                              n_queries=4),
                    RecurrentAttentionLayer(n_in=8, n_out=16)):
            lay2 = Layer.from_map(lay.to_map())
            assert lay2 == lay

    def test_gradcheck_self_attention(self):
        """Analytic vs numeric gradients (reference:
        AttentionLayerTest gradient checks, SURVEY.md 4.5)."""
        lay = SelfAttentionLayer(n_in=4, n_out=4, n_heads=2)
        p = lay.init_params(jax.random.PRNGKey(0), InputTypeRecurrent(4))
        x = jnp.asarray(np.random.RandomState(0).randn(2, 5, 4),
                        jnp.float64 if jax.config.read("jax_enable_x64")
                        else jnp.float32)

        def loss(params):
            y, _ = lay.forward(params, x, training=False)
            return jnp.sum(y ** 2)

        g = jax.grad(loss)(p)
        eps = 1e-3
        for name in ("Wq", "Wo"):
            w = np.asarray(p[name]).copy()
            idx = (0, 1)
            for sgn in (1,):
                w_p, w_m = w.copy(), w.copy()
                w_p[idx] += eps
                w_m[idx] -= eps
                lp = loss({**p, name: jnp.asarray(w_p)})
                lm = loss({**p, name: jnp.asarray(w_m)})
                num = (lp - lm) / (2 * eps)
                ana = np.asarray(g[name])[idx]
                assert abs(num - ana) / max(abs(num), 1e-3) < 5e-2


class TestAttentionInRnnPipeline:
    def test_attention_between_rnn_and_output(self):
        """Self-attention composes with RnnOutputLayer (per-step)."""
        x, _ = _seq_cls_data(n=8, t=6)
        y = np.eye(2, dtype=np.float32)[
            (x[:, :, 0] > 0).astype(int)]
        conf = (NeuralNetConfiguration.Builder()
                .seed(3).updater(Adam(1e-2)).list()
                .layer(SelfAttentionLayer(n_out=16, n_heads=2))
                .layer(RnnOutputLayer(
                    n_out=2, loss_function=LossFunction.MCXENT,
                    activation=Activation.SOFTMAX))
                .set_input_type(InputType.recurrent(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        out = net.output(x)
        assert out.shape == (8, 6, 2)
        for _ in range(30):
            net.fit(x, y)
        assert np.isfinite(net.score())
