"""Transfer learning (SURVEY.md D10) + early stopping (D12) tests."""
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator, EarlyStoppingConfiguration,
    EarlyStoppingTrainer, InMemoryModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition)
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.learning.updaters import NoOp
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration, TransferLearning)


def _blobs(n=240, n_classes=3, seed=0):
    rng = np.random.RandomState(seed)
    centers = np.eye(n_classes, 4, dtype=np.float32) * 3
    xs, ys = [], []
    for i in range(n):
        c = i % n_classes
        xs.append(centers[c] + rng.randn(4).astype(np.float32) * 0.4)
        ys.append(c)
    x = np.stack(xs)
    y = np.eye(n_classes, dtype=np.float32)[ys]
    return x, y


def _net(n_classes=3, seed=7):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Adam(5e-2))
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.RELU))
            .layer(DenseLayer(n_out=12, activation=Activation.RELU))
            .layer(OutputLayer(n_out=n_classes,
                               activation=Activation.SOFTMAX,
                               loss_function=LossFunction.MCXENT))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    return net


class TestTransferLearning:
    def _trained(self):
        x, y = _blobs()
        net = _net()
        net.fit(DataSet(x, y), n_epochs=30)
        return net

    def test_freeze_and_replace_output(self):
        src = self._trained()
        new = (TransferLearning.Builder(src)
               .fine_tune_configuration(
                   FineTuneConfiguration(updater=Adam(2e-2)))
               .set_feature_extractor(1)     # freeze layers 0..1
               .remove_output_layer()
               .add_layer(OutputLayer(n_out=2,
                                      activation=Activation.SOFTMAX,
                                      loss_function=LossFunction
                                      .MCXENT))
               .build())
        # retained layers carry the trained weights
        np.testing.assert_array_equal(
            np.asarray(src.params["layer_0"]["W"]),
            np.asarray(new.params["layer_0"]["W"]))
        assert isinstance(new.conf.layers[0].updater, NoOp)
        assert isinstance(new.conf.layers[1].updater, NoOp)

        w0_before = np.asarray(new.params["layer_0"]["W"]).copy()
        # binary relabeling of the same blobs
        x, y3 = _blobs()
        y2 = np.eye(2, dtype=np.float32)[(y3.argmax(1) > 0)
                                         .astype(int)]
        new.fit(DataSet(x, y2), n_epochs=25)
        # frozen weights unchanged, new head learns the task
        np.testing.assert_array_equal(
            w0_before, np.asarray(new.params["layer_0"]["W"]))
        pred = np.asarray(new.output(x)).argmax(1)
        acc = (pred == y2.argmax(1)).mean()
        assert acc > 0.9, acc

    def test_n_out_replace(self):
        src = self._trained()
        new = (TransferLearning.Builder(src)
               .n_out_replace(1, 20)
               .build())
        assert new.params["layer_1"]["W"].shape == (16, 20)
        assert new.params["layer_2"]["W"].shape == (20, 3)
        # layer 0 retained
        np.testing.assert_array_equal(
            np.asarray(src.params["layer_0"]["W"]),
            np.asarray(new.params["layer_0"]["W"]))
        # still trainable end-to-end
        x, y = _blobs()
        new.fit(DataSet(x, y), n_epochs=3)
        assert np.isfinite(new.score())


class TestEarlyStopping:
    def _iters(self):
        x, y = _blobs(180, seed=1)
        train = ListDataSetIterator(DataSet(x[:120], y[:120]), 30)
        val = ListDataSetIterator(DataSet(x[120:], y[120:]), 30)
        return train, val

    def test_max_epochs_terminates(self):
        train, val = self._iters()
        conf = (EarlyStoppingConfiguration.Builder()
                .score_calculator(DataSetLossCalculator(val))
                .model_saver(InMemoryModelSaver())
                .epoch_termination_conditions(
                    MaxEpochsTerminationCondition(4))
                .build())
        res = EarlyStoppingTrainer(conf, _net(), train).fit()
        assert res.termination_reason == "EpochTermination"
        assert res.total_epochs == 4
        assert len(res.score_vs_epoch) == 4
        assert res.best_model is not None
        assert np.isfinite(res.best_model_score)

    def test_score_improvement_patience(self):
        train, val = self._iters()
        conf = (EarlyStoppingConfiguration.Builder()
                .score_calculator(DataSetLossCalculator(val))
                .epoch_termination_conditions(
                    ScoreImprovementEpochTerminationCondition(2),
                    MaxEpochsTerminationCondition(100))
                .build())
        res = EarlyStoppingTrainer(conf, _net(), train).fit()
        assert res.total_epochs < 100
        # best model scores at least as well as the final epoch
        assert res.best_model_score <= \
            list(res.score_vs_epoch.values())[-1] + 1e-6

    def test_divergence_guard_aborts(self):
        train, val = self._iters()
        conf = (EarlyStoppingConfiguration.Builder()
                .score_calculator(DataSetLossCalculator(val))
                .iteration_termination_conditions(
                    MaxScoreIterationTerminationCondition(1e-9))
                .epoch_termination_conditions(
                    MaxEpochsTerminationCondition(50))
                .build())
        res = EarlyStoppingTrainer(conf, _net(), train).fit()
        assert res.termination_reason == "IterationTermination"
        assert res.total_epochs == 0
