"""Zoo breadth tests (SURVEY.md D15): every model instantiates at a
reduced input size, runs forward with correct output shape, and takes
a finite training step. YOLO models additionally train against the
Yolo2OutputLayer loss."""
import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo_extra import (
    Darknet19, InceptionResNetV1, NASNet, SqueezeNet,
    TextGenerationLSTM, TinyYOLO, UNet, Xception, YOLO2)


def _img(b, h, w, c=3, seed=0):
    return np.random.RandomState(seed).randn(b, h, w, c) \
        .astype(np.float32)


def _onehot(n, k, seed=0):
    rng = np.random.RandomState(seed)
    return np.eye(k, dtype=np.float32)[rng.randint(0, k, n)]


class TestClassifiers:
    @pytest.mark.parametrize("cls,kw,hw", [
        (Darknet19, {}, 64),
        (SqueezeNet, {}, 64),
        (Xception, {"middle_blocks": 1}, 71),
        (InceptionResNetV1, {"blocks": (1, 1, 1)}, 80),
        (NASNet, {"cells_per_stack": 1,
                  "penultimate_filters": 264}, 64),
    ])
    def test_forward_and_fit(self, cls, kw, hw):
        net = cls(num_classes=7, height=hw, width=hw, **kw).init()
        x = _img(2, hw, hw)
        out = np.asarray(net.output(x))
        assert out.shape == (2, 7)
        np.testing.assert_allclose(out.sum(1), 1.0, atol=1e-4)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        net.fit(DataSet(x, _onehot(2, 7)))
        assert np.isfinite(net.score())


class TestUNet:
    def test_segmentation_shapes(self):
        net = UNet(height=32, width=32, base_filters=8, depth=2).init()
        x = _img(2, 32, 32)
        out = np.asarray(net.output(x))
        assert out.shape == (2, 32, 32, 1)
        assert (out >= 0).all() and (out <= 1).all()
        # binary masks -> finite XENT loss step
        y = (np.random.RandomState(1).rand(2, 32, 32, 1) > 0.5) \
            .astype(np.float32)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        net.fit(DataSet(x, y))
        assert np.isfinite(net.score())


class TestYolo:
    def _labels(self, b, h, w, n_classes, seed=0):
        """A few random cells get a box + class."""
        rng = np.random.RandomState(seed)
        lab = np.zeros((b, h, w, 4 + n_classes), np.float32)
        for bi in range(b):
            for _ in range(3):
                i, j = rng.randint(h), rng.randint(w)
                lab[bi, i, j, 0:2] = rng.rand(2)          # cx, cy
                lab[bi, i, j, 2:4] = 0.5 + rng.rand(2) * 3  # w, h
                lab[bi, i, j, 4 + rng.randint(n_classes)] = 1.0
        return lab

    def test_tiny_yolo_trains(self):
        net = TinyYOLO(num_classes=4, height=64, width=64).init()
        x = _img(2, 64, 64)
        out = np.asarray(net.output(x))
        a = len(TinyYOLO().anchors)
        assert out.shape == (2, 2, 2, a * (5 + 4))
        from deeplearning4j_tpu.datasets.dataset import DataSet
        lab = self._labels(2, 2, 2, 4)
        scores = []
        for i in range(12):
            net.fit(DataSet(x, lab))
            scores.append(float(net.score()))
        assert np.isfinite(scores).all()
        # noisy early (BN+Adam warmup) but converging
        assert np.mean(scores[-3:]) < scores[0], scores

    def test_yolo2_instantiates(self):
        net = YOLO2(num_classes=3, height=64, width=64).init()
        out = np.asarray(net.output(_img(1, 64, 64)))
        a = len(YOLO2().anchors)
        assert out.shape == (1, 2, 2, a * (5 + 3))


class TestTextGeneration:
    def test_char_lstm_trains(self):
        net = TextGenerationLSTM(total_unique_characters=12,
                                 max_length=16, units=32,
                                 layers=2).init()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 12, (4, 16))
        x = np.eye(12, dtype=np.float32)[ids].astype(np.float32)
        y = np.eye(12, dtype=np.float32)[np.roll(ids, -1, 1)]
        from deeplearning4j_tpu.datasets.dataset import DataSet
        net.fit(DataSet(x, y), n_epochs=3)
        assert np.isfinite(net.score())
        out = np.asarray(net.output(x))
        assert out.shape == (4, 16, 12)
