"""Keras import conformance for the extended mapper set (SURVEY.md
D14/§4.6): conv 1D/3D/transpose/separable/depthwise, pooling 1D/3D,
crop/pad/upsample/repeat, PReLU, TimeDistributed, Bidirectional.
Protocol: build+save with the in-image Keras, import, compare outputs.
"""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
keras = tf.keras

from deeplearning4j_tpu.modelimport.keras import (  # noqa: E402
    InvalidKerasConfigurationException, KerasModelImport)


def _compare(model, x, tmp_path, atol=1e-4):
    path = str(tmp_path / "model.keras")
    model.save(path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(
        path)
    want = np.asarray(model(x, training=False))
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, want, atol=atol, rtol=1e-3)
    return net


R = np.random.RandomState(0)


class TestConvFamily:
    def test_conv1d_pool1d(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((12, 3)),
            keras.layers.Conv1D(8, 3, padding="same",
                                activation="relu"),
            keras.layers.MaxPooling1D(2),
            keras.layers.Conv1D(4, 3, padding="valid", strides=2),
            keras.layers.GlobalAveragePooling1D(),
            keras.layers.Dense(5, activation="softmax"),
        ])
        _compare(model, R.randn(4, 12, 3).astype(np.float32), tmp_path)

    def test_conv1d_causal(self, tmp_path):
        """WaveNet-style causal padding (regression: was silently
        imported as valid)."""
        model = keras.Sequential([
            keras.layers.Input((12, 2)),
            keras.layers.Conv1D(4, 3, padding="causal",
                                dilation_rate=2),
            keras.layers.Conv1D(2, 3, padding="causal"),
        ])
        _compare(model, R.randn(2, 12, 2).astype(np.float32), tmp_path)

    def test_conv3d_model_roundtrips(self, tmp_path):
        """Conv3D nets serialize with the auto-inserted 3D preprocessor
        (regression: Cnn3DToFeedForwardPreProcessor missing from the
        serde registry)."""
        from deeplearning4j_tpu.utils.serializer import ModelSerializer
        model = keras.Sequential([
            keras.layers.Input((4, 4, 4, 1)),
            keras.layers.Conv3D(2, 2),
            keras.layers.Flatten(),
            keras.layers.Dense(3),
        ])
        path = str(tmp_path / "m.keras")
        model.save(path)
        net = KerasModelImport \
            .import_keras_sequential_model_and_weights(path)
        x = R.randn(2, 4, 4, 4, 1).astype(np.float32)
        want = np.asarray(net.output(x))
        zpath = str(tmp_path / "net.zip")
        ModelSerializer.write_model(net, zpath, save_updater=False)
        net2 = ModelSerializer.restore_multi_layer_network(zpath)
        np.testing.assert_allclose(np.asarray(net2.output(x)), want,
                                   rtol=1e-5)

    def test_conv3d_pool3d(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((6, 6, 6, 2)),
            keras.layers.Conv3D(4, 3, padding="same",
                                activation="relu"),
            keras.layers.MaxPooling3D(2),
            keras.layers.Conv3D(2, 2, padding="valid"),
            keras.layers.Flatten(),
            keras.layers.Dense(3),
        ])
        _compare(model, R.randn(2, 6, 6, 6, 2).astype(np.float32),
                 tmp_path)

    def test_conv2d_transpose(self, tmp_path):
        for pad, stride in (("same", 2), ("valid", 2), ("same", 1)):
            model = keras.Sequential([
                keras.layers.Input((5, 5, 3)),
                keras.layers.Conv2DTranspose(4, 3, strides=stride,
                                             padding=pad),
            ])
            _compare(model, R.randn(2, 5, 5, 3).astype(np.float32),
                     tmp_path)

    def test_separable_and_depthwise(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((8, 8, 3)),
            keras.layers.SeparableConv2D(6, 3, padding="same",
                                         activation="relu"),
            keras.layers.DepthwiseConv2D(3, padding="valid",
                                         depth_multiplier=2),
            keras.layers.GlobalAveragePooling2D(),
        ])
        _compare(model, R.randn(2, 8, 8, 3).astype(np.float32),
                 tmp_path)


class TestShapeFamily:
    def test_crop_pad_upsample_2d(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((8, 8, 2)),
            keras.layers.ZeroPadding2D(((1, 2), (0, 1))),
            keras.layers.Cropping2D(((0, 1), (2, 0))),
            keras.layers.UpSampling2D(2),
            keras.layers.Conv2D(2, 1),
        ])
        _compare(model, R.randn(2, 8, 8, 2).astype(np.float32),
                 tmp_path)

    def test_crop_pad_upsample_1d(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((10, 3)),
            keras.layers.ZeroPadding1D((1, 2)),
            keras.layers.Cropping1D((2, 1)),
            keras.layers.UpSampling1D(2),
            keras.layers.Conv1D(2, 1),
        ])
        _compare(model, R.randn(2, 10, 3).astype(np.float32), tmp_path)

    def test_pad_upsample_3d(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((4, 4, 4, 1)),
            keras.layers.ZeroPadding3D(((1, 0), (0, 1), (1, 1))),
            keras.layers.UpSampling3D(2),
            keras.layers.Cropping3D(((1, 1), (0, 2), (2, 0))),
        ])
        _compare(model, R.randn(1, 4, 4, 4, 1).astype(np.float32),
                 tmp_path)

    def test_repeat_vector(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((6,)),
            keras.layers.Dense(4, activation="tanh"),
            keras.layers.RepeatVector(3),
            keras.layers.LSTM(5, return_sequences=True),
        ])
        _compare(model, R.randn(2, 6).astype(np.float32), tmp_path)


class TestMiscAndWrappers:
    def test_prelu(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((7,)),
            keras.layers.Dense(5),
            keras.layers.PReLU(),
        ])
        # non-trivial alphas
        model.layers[-1].set_weights(
            [R.rand(5).astype(np.float32) * 0.5])
        _compare(model, R.randn(3, 7).astype(np.float32), tmp_path)

    def test_time_distributed_dense(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((4, 6)),
            keras.layers.TimeDistributed(
                keras.layers.Dense(3, activation="relu")),
        ])
        _compare(model, R.randn(2, 4, 6).astype(np.float32), tmp_path)

    def test_bidirectional_lstm(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((5, 4)),
            keras.layers.Bidirectional(
                keras.layers.LSTM(6, return_sequences=True)),
        ])
        _compare(model, R.randn(2, 5, 4).astype(np.float32), tmp_path)

    def test_bidirectional_sum_mode(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((5, 4)),
            keras.layers.Bidirectional(
                keras.layers.SimpleRNN(6, return_sequences=True),
                merge_mode="sum"),
        ])
        _compare(model, R.randn(2, 5, 4).astype(np.float32), tmp_path)

    def test_noise_layers_import_as_inference_identity(self, tmp_path):
        """GaussianNoise/GaussianDropout/AlphaDropout import and are
        identity at inference, matching keras."""
        model = keras.Sequential([
            keras.layers.Input((6,)),
            keras.layers.Dense(5, activation="tanh"),
            keras.layers.GaussianNoise(0.3),
            keras.layers.GaussianDropout(0.2),
            keras.layers.AlphaDropout(0.1),
            keras.layers.Dense(3),
        ])
        _compare(model, R.randn(4, 6).astype(np.float32), tmp_path)

    def test_bidirectional_no_sequences_rejected(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((5, 4)),
            keras.layers.Bidirectional(keras.layers.LSTM(6)),
        ])
        path = str(tmp_path / "model.keras")
        model.save(path)
        with pytest.raises(InvalidKerasConfigurationException,
                           match="return_sequences"):
            KerasModelImport.import_keras_sequential_model_and_weights(
                path)
