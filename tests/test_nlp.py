"""NLP subsystem tests (SURVEY.md D16: tokenizers, Word2Vec,
ParagraphVectors, BertIterator)."""
import numpy as np
import pytest

from deeplearning4j_tpu.nlp import (BertIterator, BertWordPieceTokenizer,
                                    CommonPreprocessor,
                                    DefaultTokenizerFactory,
                                    ParagraphVectors, Word2Vec,
                                    build_vocab)


class TestTokenizers:
    def test_default_tokenizer_with_preprocessor(self):
        tf = DefaultTokenizerFactory()
        tf.set_token_pre_processor(CommonPreprocessor())
        tk = tf.create("Hello, World!  FOO-bar 42.")
        assert tk.get_tokens() == ["hello", "world", "foobar", "42"]
        assert tk.count_tokens() == 4
        assert tk.has_more_tokens()
        assert tk.next_token() == "hello"

    def test_wordpiece_classic(self):
        vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
                 "un", "##aff", "##able", "runn", "##ing", "the"]
        tk = BertWordPieceTokenizer(vocab)
        assert tk.tokenize("unaffable") == ["un", "##aff", "##able"]
        assert tk.tokenize("THE unaffable") == ["the", "un", "##aff",
                                                "##able"]
        assert tk.tokenize("xyzzy") == ["[UNK]"]

    def test_wordpiece_punctuation_split(self):
        vocab = ["[UNK]", "hello", "world", ",", "!"]
        tk = BertWordPieceTokenizer(vocab)
        assert tk.tokenize("hello, world!") == ["hello", ",", "world",
                                                "!"]

    def test_vocab_builder_roundtrip(self):
        corpus = ["the quick brown fox", "the lazy dog",
                  "the quick dog"]
        vocab = BertWordPieceTokenizer.build_vocab(corpus, size=64)
        tk = BertWordPieceTokenizer(vocab)
        ids = tk.encode("the quick dog")
        assert tk.vocab["[UNK]"] not in ids
        assert len(ids) == 3


def _two_cluster_corpus(n=300, seed=0):
    """Sentences drawn from two disjoint co-occurrence clusters."""
    rng = np.random.RandomState(seed)
    a = ["apple", "banana", "cherry", "grape"]
    b = ["bolt", "nut", "wrench", "hammer"]
    out = []
    for _ in range(n):
        words = a if rng.rand() < 0.5 else b
        out.append(" ".join(rng.choice(words, 6)))
    return out, a, b


class TestWord2Vec:
    def test_cluster_similarity(self):
        corpus, a, b = _two_cluster_corpus()
        w2v = (Word2Vec.Builder()
               .min_word_frequency(2).layer_size(24).window_size(3)
               .negative_sample(5).epochs(8).seed(7)
               .learning_rate(0.0025)   # tiny vocab: see class doc
               .iterate(corpus).build())
        w2v.fit()
        intra = w2v.similarity("apple", "banana")
        inter = w2v.similarity("apple", "wrench")
        assert intra > inter + 0.2, (intra, inter)
        near = w2v.words_nearest("bolt", 3)
        assert set(near) <= set(b), near

    def test_vector_api(self):
        corpus, a, b = _two_cluster_corpus(100)
        w2v = Word2Vec(layer_size=8, epochs=1, seed=1)
        w2v.fit(corpus)
        assert w2v.has_word("apple")
        assert not w2v.has_word("zebra")
        assert w2v.get_word_vector("apple").shape == (8,)
        assert w2v.get_word_vector_matrix().shape[1] == 8


class TestParagraphVectors:
    def test_doc_clusters_and_inference(self):
        corpus, a, b = _two_cluster_corpus(120, seed=3)
        labels = [f"D{i}" for i in range(len(corpus))]
        pv = ParagraphVectors(layer_size=16, epochs=50, seed=5,
                              negative=5, learning_rate=0.02)
        pv.fit(corpus, labels)
        # infer a new fruit-doc: closer to fruit docs than tool docs
        v = pv.infer_vector("apple cherry banana grape apple cherry",
                            steps=300, learning_rate=0.08)
        sims = pv.doc_vectors @ v / (
            np.linalg.norm(pv.doc_vectors, axis=1)
            * np.linalg.norm(v) + 1e-12)
        fruit = [i for i, s in enumerate(corpus) if "apple" in s
                 or "banana" in s or "cherry" in s or "grape" in s]
        tools = [i for i in range(len(corpus)) if i not in fruit]
        assert sims[fruit].mean() > sims[tools].mean() + 0.1


class TestBertIterator:
    def _tokenizer(self):
        corpus = ["the quick brown fox jumps over the lazy dog"] * 4
        vocab = BertWordPieceTokenizer.build_vocab(corpus, size=128)
        return BertWordPieceTokenizer(vocab)

    def test_shapes_and_special_tokens(self):
        tk = self._tokenizer()
        sents = ["the quick brown fox", "the lazy dog"] * 4
        it = BertIterator(tk, sents, max_length=16, batch_size=4)
        batch = it.next()
        assert batch["input_ids"].shape == (4, 16)
        assert batch["attention_mask"].shape == (4, 16)
        assert (batch["input_ids"][:, 0] == tk.id_of("[CLS]")).all()
        # mlm task: labels -1 on unmasked, original ids on masked
        lab = batch["mlm_labels"]
        assert ((lab == -1) | (lab >= 0)).all()

    def test_masking_statistics(self):
        tk = self._tokenizer()
        sents = ["the quick brown fox jumps over the lazy dog"] * 64
        it = BertIterator(tk, sents, max_length=16, batch_size=64,
                          mask_prob=0.15, seed=2)
        b = it.next()
        real = np.isin(b["input_ids"], [tk.id_of("[PAD]"),
                                        tk.id_of("[CLS]"),
                                        tk.id_of("[SEP]")],
                       invert=True)
        n_masked = (b["mlm_labels"] >= 0).sum()
        n_maskable = real.sum() + (
            b["input_ids"] == tk.id_of("[MASK]")).sum()
        frac = n_masked / n_maskable
        assert 0.08 < frac < 0.25, frac

    def test_feeds_bert_pretraining(self):
        tk = self._tokenizer()
        sents = ["the quick brown fox jumps", "the lazy dog sleeps",
                 "quick dog over fox", "lazy fox the dog"] * 2
        it = BertIterator(tk, sents, max_length=12, batch_size=8,
                          seed=0)
        from deeplearning4j_tpu.models.bert import Bert, BertConfig
        conf = BertConfig.tiny(vocab_size=len(tk.vocab),
                               max_position_embeddings=12)
        bert = Bert(conf).init()
        it.reset()
        losses = []
        for _ in range(6):
            if not it.has_next():
                it.reset()
            losses.append(bert.fit_batch(it.next()))
        assert np.isfinite(losses).all()

    def test_sentence_pair_segment_ids(self):
        tk = self._tokenizer()
        it = BertIterator(tk, [("the quick fox", "the lazy dog")],
                          max_length=16, batch_size=1, seed=0,
                          task=BertIterator.SEQ_CLASSIFICATION,
                          labels=[0], n_labels=2)
        b = it.next()
        ids = b["input_ids"][0]
        tt = b["token_type_ids"][0]
        sep = tk.id_of("[SEP]")
        first_sep = int(np.argmax(ids == sep))
        # segment 0 through the first [SEP], segment 1 after it up to
        # (and including) the second [SEP], 0 again on padding
        assert (tt[:first_sep + 1] == 0).all()
        second_sep = first_sep + 1 + int(
            np.argmax(ids[first_sep + 1:] == sep))
        assert (tt[first_sep + 1:second_sep + 1] == 1).all()
        assert (tt[second_sep + 1:] == 0).all()

    def test_classification_task(self):
        tk = self._tokenizer()
        sents = ["the quick fox", "lazy dog", "quick dog",
                 "lazy fox"]
        it = BertIterator(tk, sents, max_length=8, batch_size=4,
                          task=BertIterator.SEQ_CLASSIFICATION,
                          labels=[0, 1, 0, 1])
        b = it.next()
        assert b["labels"].shape == (4, 2)
        assert (b["labels"].sum(1) == 1).all()
        assert "mlm_labels" not in b


class TestGlove:
    def _corpus(self):
        # two topical clusters so co-occurrence separates them
        animals = "the cat chased the dog while the dog chased the cat"
        royals = "the king ruled the queen and the queen ruled the king"
        return ([animals] * 20 + [royals] * 20 +
                ["cat and dog are animals"] * 10 +
                ["king and queen are royals"] * 10)

    def test_trains_and_clusters(self):
        from deeplearning4j_tpu.nlp import Glove
        g = (Glove.Builder()
             .iterate(self._corpus())
             .layer_size(16).window_size(4)
             .learning_rate(0.05).epochs(60).seed(7)
             .build())
        g.fit()
        assert g.has_word("cat") and g.has_word("king")
        # within-topic similarity beats cross-topic
        assert g.similarity("cat", "dog") > g.similarity("cat", "queen")
        assert g.similarity("king", "queen") > \
            g.similarity("king", "dog")

    def test_vectors_finite_and_lookup_api(self):
        from deeplearning4j_tpu.nlp import Glove
        g = (Glove.Builder().iterate(self._corpus())
             .layer_size(8).epochs(5).build())
        g.fit()
        v = g.get_word_vector("cat")
        assert v.shape == (8,)
        assert np.isfinite(v).all()
        near = g.words_nearest("cat", 3)
        assert len(near) == 3 and "cat" not in near


class TestHierarchicalSoftmax:
    def test_huffman_paths_are_prefix_free_and_frequency_ordered(self):
        from deeplearning4j_tpu.nlp.word2vec import build_huffman
        counts = np.asarray([100, 50, 20, 10, 5, 2, 1])
        nodes, codes, mask = build_huffman(counts)
        v = len(counts)
        assert nodes.shape == codes.shape == mask.shape
        assert nodes.max() <= v - 2
        lens = mask.sum(1)
        # Huffman property: more frequent words get shorter codes
        assert lens[0] == lens.min()
        assert lens[-1] == lens.max()
        # prefix-free: no full path equals the prefix of another
        paths = [tuple(zip(nodes[w][:int(lens[w])],
                           codes[w][:int(lens[w])])) for w in range(v)]
        for i in range(v):
            for j in range(v):
                if i != j:
                    assert paths[i] != paths[j][:len(paths[i])]

    def test_hs_paragraph_vectors_infer(self):
        """PV-DBOW with HS: inference must use the Huffman-path
        objective (regression: it indexed the [V-1] internal-node
        table with word ids and silently clamped) — same relative
        cluster gate as the SGNS inference test."""
        from deeplearning4j_tpu.nlp.word2vec import ParagraphVectors
        corpus, a, b = _two_cluster_corpus(120, seed=3)
        pv = ParagraphVectors(layer_size=16, epochs=50, seed=5,
                              learning_rate=0.02,
                              use_hierarchic_softmax=True)
        pv.fit(corpus)
        v = pv.infer_vector("apple cherry banana grape apple cherry",
                            steps=300, learning_rate=0.08)
        sims = pv.doc_vectors @ v / (
            np.linalg.norm(pv.doc_vectors, axis=1)
            * np.linalg.norm(v) + 1e-12)
        fruit = [i for i, s in enumerate(corpus) if "apple" in s
                 or "banana" in s or "cherry" in s or "grape" in s]
        tools = [i for i in range(len(corpus)) if i not in fruit]
        assert sims[fruit].mean() > sims[tools].mean() + 0.1

    def test_hs_word2vec_clusters(self):
        """Same two-cluster quality gate as the SGNS test, trained
        with useHierarchicSoftmax (reference mode parity)."""
        corpus, a, b = _two_cluster_corpus(100)
        w2v = (Word2Vec.Builder()
               .min_word_frequency(2).layer_size(24).window_size(3)
               .use_hierarchic_softmax(True).epochs(8).seed(7)
               .learning_rate(0.0025)
               .iterate(corpus).build())
        w2v.fit()
        # HS output table has V-1 internal nodes
        assert w2v.syn1.shape[0] == len(w2v.vocab) - 1
        intra = w2v.similarity("apple", "banana")
        inter = w2v.similarity("apple", "wrench")
        assert intra > inter + 0.2, (intra, inter)
