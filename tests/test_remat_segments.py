"""Segment rematerialization (`remat_segments`): training forward cut
into jax.checkpoint'd segments must be a pure memory/runtime trade —
losses, gradients, and trained params must match the plain path.

TPU-first extension (no reference equivalent): the reference's
workspace machinery manages activation memory imperatively
(SURVEY.md D8/J6); on XLA the equivalent lever is sqrt(N)
checkpointing of the forward walk."""
import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, ConvolutionLayer, DenseLayer, OutputLayer,
    SubsamplingLayer)
from deeplearning4j_tpu.nn.graph import ComputationGraph


def _mln_conf(remat_segments=0):
    return (NeuralNetConfiguration.Builder()
            .seed(7).updater(Adam(1e-2))
            .remat_segments(remat_segments)
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                    activation=Activation.RELU))
            .layer(BatchNormalization())
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                    activation=Activation.RELU))
            .layer(SubsamplingLayer(kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=16,
                              activation=Activation.RELU))
            .layer(OutputLayer(n_out=4,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.convolutional(12, 12, 3))
            .build())


def _batch(n=8, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 12, 12, 3).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, n)]
    return DataSet(x, y)


class TestMlnRematSegments:
    def test_training_matches_plain(self):
        """Same seed, same batches: scores and params must track the
        un-remated run (identical math, re-scheduled)."""
        ds = _batch()
        a = MultiLayerNetwork(_mln_conf(0)).init()
        b = MultiLayerNetwork(_mln_conf(3)).init()
        for la, lb in zip(
                np.asarray(
                    [float(a.params[k][w].sum()) for k in a.params
                     for w in a.params[k]]),
                np.asarray(
                    [float(b.params[k][w].sum()) for k in b.params
                     for w in b.params[k]])):
            np.testing.assert_allclose(la, lb, rtol=1e-6)
        for _ in range(5):
            a.fit(ds)
            b.fit(ds)
        np.testing.assert_allclose(a.score(), b.score(),
                                   rtol=1e-4, atol=1e-5)
        for k in a.params:
            for w in a.params[k]:
                np.testing.assert_allclose(
                    np.asarray(a.params[k][w]),
                    np.asarray(b.params[k][w]),
                    rtol=2e-3, atol=2e-4)

    def test_inference_ignores_remat(self):
        """output() (training=False) is identical regardless of the
        remat setting — the knob only reschedules training."""
        x = _batch().features
        a = MultiLayerNetwork(_mln_conf(0)).init()
        b = MultiLayerNetwork(_mln_conf(4)).init()
        np.testing.assert_allclose(np.asarray(a.output(x)),
                                   np.asarray(b.output(x)),
                                   rtol=1e-6)

    def test_json_round_trip(self):
        conf = _mln_conf(3)
        from deeplearning4j_tpu.nn.conf.builders import \
            MultiLayerConfiguration
        again = MultiLayerConfiguration.from_json(conf.to_json())
        assert again.remat_segments == 3


def _graph_conf(remat_segments=0):
    """Small residual graph: conv trunk with a skip-add (fan-out
    crossing segment boundaries exercises the liveness logic)."""
    from deeplearning4j_tpu.nn.conf.graph_vertices import (
        ElementWiseVertex)
    gb = (NeuralNetConfiguration.Builder()
          .seed(11).updater(Adam(1e-2))
          .graph_builder()
          .add_inputs("in")
          .set_input_types(InputType.convolutional(12, 12, 3)))
    gb.add_layer("c1", ConvolutionLayer(
        n_out=8, kernel_size=(3, 3), activation=Activation.RELU),
        "in")
    gb.add_layer("bn1", BatchNormalization(), "c1")
    gb.add_layer("c2", ConvolutionLayer(
        n_out=8, kernel_size=(1, 1),
        activation=Activation.IDENTITY), "bn1")
    gb.add_vertex("add", ElementWiseVertex(ElementWiseVertex.Op.Add),
                  "bn1", "c2")
    gb.add_layer("pool", SubsamplingLayer(kernel_size=(2, 2),
                                          stride=(2, 2)), "add")
    gb.add_layer("d1", DenseLayer(n_out=16,
                                  activation=Activation.RELU),
                 "pool")
    gb.add_layer("out", OutputLayer(
        n_out=4, loss_function=LossFunction.MCXENT,
        activation=Activation.SOFTMAX), "d1")
    gb.set_outputs("out")
    conf = gb.remat_segments(remat_segments).build() \
        if remat_segments else gb.build()
    return conf


class TestGraphRematSegments:
    def test_training_matches_plain(self):
        ds = _batch(seed=3)
        a = ComputationGraph(_graph_conf(0)).init()
        b = ComputationGraph(_graph_conf(3)).init()
        for _ in range(5):
            a.fit(ds)
            b.fit(ds)
        np.testing.assert_allclose(a.score(), b.score(),
                                   rtol=1e-4, atol=1e-5)
        for k in a.params:
            for w in a.params[k]:
                np.testing.assert_allclose(
                    np.asarray(a.params[k][w]),
                    np.asarray(b.params[k][w]),
                    rtol=2e-3, atol=2e-4,
                    err_msg=f"{k}/{w}")

    def test_skip_connection_across_boundary(self):
        """A fan-out activation consumed beyond the next boundary must
        survive segment pruning (the liveness set, not a lucky
        adjacency)."""
        ds = _batch(seed=4)
        # 7 vertices, 6 segments -> nearly every vertex is a boundary
        b = ComputationGraph(_graph_conf(6)).init()
        for _ in range(3):
            b.fit(ds)
        assert np.isfinite(b.score())

    def test_json_round_trip(self):
        from deeplearning4j_tpu.nn.conf.graph_conf import \
            ComputationGraphConfiguration
        conf = _graph_conf(4)
        again = ComputationGraphConfiguration.from_json(conf.to_json())
        assert again.remat_segments == 4


def test_oversized_segment_count_clamps_to_per_layer():
    """remat_segments >= layer count must clamp to per-layer
    checkpointing, not silently disable (code-review regression)."""
    ds = _batch(seed=5)
    net = MultiLayerNetwork(_mln_conf(200)).init()
    for _ in range(3):
        net.fit(ds)
    assert np.isfinite(net.score())
    g = ComputationGraph(_graph_conf(200)).init()
    for _ in range(3):
        g.fit(ds)
    assert np.isfinite(g.score())


class TestSameDiffRematSegments:
    """`SameDiff.set_remat_segments(n)`: training programs (fit and
    fit_steps) cut the op walk into jax.checkpoint segments — the
    memory lever for FLAT imported graphs (no layer structure to
    remat). Must be a pure re-schedule: identical losses and params."""

    @staticmethod
    def _build(segs):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        from deeplearning4j_tpu.autodiff.training import TrainingConfig
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 8))
        y = sd.placeholder("y", shape=(None, 4))
        h = x
        rng = np.random.RandomState(3)
        for i in range(6):
            w = sd.var(f"w{i}", array=(rng.randn(8, 8) * 0.3)
                       .astype(np.float32))
            h = sd.nn.tanh(h @ w)
            if i == 2:
                # an RNG op mid-walk pins the contract that
                # segmentation does not change the random stream
                # (per-op rng is fold_in(rng, GLOBAL op idx))
                h = sd.nn.dropout(h, 0.25)
        wo = sd.var("wo", array=(rng.randn(8, 4) * 0.3)
                    .astype(np.float32))
        sd.loss.mean_squared_error(y, h @ wo, name="loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(
            TrainingConfig.Builder().updater(Adam(0.01))
            .data_set_feature_mapping("x")
            .data_set_label_mapping("y").build())
        if segs:
            sd.set_remat_segments(segs)
        return sd

    def test_training_matches_plain(self):
        rng = np.random.RandomState(0)
        xv = rng.randn(32, 8).astype(np.float32)
        yv = rng.randn(32, 4).astype(np.float32)
        batch = {"x": xv, "y": yv}
        a = self._build(0)
        b = self._build(4)
        la = a.fit_steps(batch, 8)
        lb = b.fit_steps(batch, 8)
        np.testing.assert_allclose(lb, la, rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(
            np.asarray(b.get_variable("w0").get_arr()),
            np.asarray(a.get_variable("w0").get_arr()),
            rtol=1e-5, atol=1e-6)

    def test_oversized_clamps(self):
        rng = np.random.RandomState(0)
        batch = {"x": rng.randn(8, 8).astype(np.float32),
                 "y": rng.randn(8, 4).astype(np.float32)}
        sd = self._build(10_000)
        assert np.isfinite(sd.fit_steps(batch, 2))

    def test_setter_invalidates_compiled_programs(self):
        """Changing the segmentation after compiling must retrace —
        the setting is baked into the program."""
        rng = np.random.RandomState(0)
        batch = {"x": rng.randn(8, 8).astype(np.float32),
                 "y": rng.randn(8, 4).astype(np.float32)}
        sd = self._build(0)
        sd.fit_steps(batch, 2)
        assert sd._exec_cache
        sd.set_remat_segments(3)
        assert not sd._exec_cache
        assert np.isfinite(sd.fit_steps(batch, 2))


class TestRngStreamInvariance:
    """Toggling remat_segments must not change the dropout/weight-noise
    random stream (r4 advisor finding: the segmented paths pre-split
    while the plain paths split sequentially; both now derive
    fold_in(rng, layer index))."""

    def _dropout_conf(self, remat_segments):
        return (NeuralNetConfiguration.Builder()
                .seed(11).updater(Adam(1e-2))
                .remat_segments(remat_segments)
                .list()
                .layer(DenseLayer(n_out=32, dropout=0.5,
                                  activation=Activation.RELU))
                .layer(DenseLayer(n_out=32, dropout=0.5,
                                  activation=Activation.RELU))
                .layer(DenseLayer(n_out=32, dropout=0.5,
                                  activation=Activation.RELU))
                .layer(OutputLayer(n_out=4,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(16))
                .build())

    def test_mln_dropout_stream_invariant(self):
        rng = np.random.RandomState(0)
        x = rng.randn(16, 16).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[rng.randint(0, 4, 16)]
        ds = DataSet(x, y)
        a = MultiLayerNetwork(self._dropout_conf(0)).init()
        b = MultiLayerNetwork(self._dropout_conf(2)).init()
        for _ in range(3):
            a.fit(ds)
            b.fit(ds)
        # EXACT same dropout masks -> near-identical params (tolerance
        # only for checkpoint recompute reassociation)
        for k in a.params:
            for w in a.params[k]:
                np.testing.assert_allclose(
                    np.asarray(a.params[k][w]),
                    np.asarray(b.params[k][w]),
                    rtol=1e-4, atol=1e-5, err_msg=f"{k}/{w}")

    def test_graph_dropout_stream_invariant(self):
        def conf(remat_segments):
            g = (NeuralNetConfiguration.Builder()
                 .seed(13).updater(Adam(1e-2))
                 .remat_segments(remat_segments)
                 .graph_builder()
                 .add_inputs("in"))
            g.add_layer("d1", DenseLayer(n_out=24, dropout=0.5,
                                         activation=Activation.RELU),
                        "in")
            g.add_layer("d2", DenseLayer(n_out=24, dropout=0.5,
                                         activation=Activation.RELU),
                        "d1")
            g.add_layer("d3", DenseLayer(n_out=24, dropout=0.5,
                                         activation=Activation.RELU),
                        "d2")
            g.add_layer("out", OutputLayer(
                n_out=3, loss_function=LossFunction.MCXENT,
                activation=Activation.SOFTMAX), "d3")
            g.set_outputs("out")
            g.set_input_types(InputType.feed_forward(10))
            return g.build()

        rng = np.random.RandomState(1)
        x = rng.randn(12, 10).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 12)]
        a = ComputationGraph(conf(0)).init()
        b = ComputationGraph(conf(2)).init()
        for _ in range(3):
            a.fit([x], [y])
            b.fit([x], [y])
        for k in a.params:
            for w in a.params[k]:
                np.testing.assert_allclose(
                    np.asarray(a.params[k][w]),
                    np.asarray(b.params[k][w]),
                    rtol=1e-4, atol=1e-5, err_msg=f"{k}/{w}")


class TestMinCutBoundaries:
    """Boundary placement by liveness (r5): on a flat op walk the
    cuts must land where the fewest values are live — the layer
    boundaries of an imported transformer — not at fixed even
    indices."""

    def test_plan_prefers_low_cost_indices(self):
        from deeplearning4j_tpu.common.remat import (
            min_cut_segment_plan, segment_plan)
        n = 100
        cost = np.full(n + 1, 10.0)
        # pinches at 23 and 71; even cuts for 3 segments are 33/66
        cost[23] = 1.0
        cost[71] = 1.0
        plan = min_cut_segment_plan(n, 3, cost)
        bounds = [lo for lo, _, _ in plan] + [plan[-1][1]]
        assert bounds == [0, 23, 71, 100]
        # flat cost degrades to the even plan
        flat = min_cut_segment_plan(n, 3, np.zeros(n + 1))
        assert flat == segment_plan(n, 3)
        # boundaries stay strictly monotone even with one global min
        one = np.full(n + 1, 5.0)
        one[50] = 0.0
        p2 = min_cut_segment_plan(n, 4, one)
        bs = [lo for lo, _, _ in p2] + [n]
        assert bs == sorted(set(bs)), bs

    def test_samediff_cut_costs_find_the_pinch(self):
        """A graph with a wide interior (many live values) and a
        single-value pinch between blocks: the cut cost at the pinch
        must be the minimum."""
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(4, 8))

        def block(h):
            a = sd.math.mul(h, h)
            b = sd.math.sin(h)
            c = sd.math.add(a, b)       # a and b live in parallel
            return sd.math.tanh(c)      # pinch: only this crosses

        h1 = block(x)
        h2 = block(h1)
        out = sd.math.reduce_sum(h2)
        ops = list(range(len(sd.ops)))
        costs = sd._segment_cut_costs(ops, (out.name,))
        # the cut between the two blocks (before op 4) is a pinch
        assert costs[4] == min(costs[1:len(sd.ops)])
        assert costs[4] < costs[2]      # mid-block is wider

    def test_segmented_training_still_matches_plain(self):
        """Min-cut boundaries keep the math identical (the boundary
        CHOICE is a schedule, not semantics)."""
        import jax
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        from deeplearning4j_tpu.autodiff.training import TrainingConfig
        from deeplearning4j_tpu.learning import Adam

        def build(segments):
            sd = SameDiff.create()
            x = sd.placeholder("x", shape=(None, 6))
            y = sd.placeholder("y", shape=(None, 1))
            h = x
            rng = np.random.RandomState(0)
            for i in range(4):
                w = sd.var(f"w{i}", array=rng.randn(
                    6, 6).astype(np.float32) * 0.3)
                h = sd.math.tanh(h @ w)
            wo = sd.var("wo", array=rng.randn(6, 1)
                        .astype(np.float32) * 0.3)
            sd.loss.mean_squared_error(y, h @ wo, name="loss")
            sd.set_loss_variables("loss")
            sd.set_training_config(
                TrainingConfig.Builder().updater(Adam(0.05))
                .data_set_feature_mapping("x")
                .data_set_label_mapping("y").build())
            if segments:
                sd.set_remat_segments(segments)
            return sd

        rng = np.random.RandomState(1)
        xv = rng.randn(32, 6).astype(np.float32)
        yv = rng.randn(32, 1).astype(np.float32)
        plain = build(0)
        seg = build(3)
        lp = plain.fit_steps({"x": xv, "y": yv}, 6)
        ls = seg.fit_steps({"x": xv, "y": yv}, 6)
        np.testing.assert_allclose(ls, lp, rtol=1e-5, atol=1e-6)
        for i in range(4):
            np.testing.assert_allclose(
                np.asarray(seg.get_variable(f"w{i}").get_arr()),
                np.asarray(plain.get_variable(f"w{i}").get_arr()),
                rtol=1e-5, atol=1e-6)

    def test_cut_costs_weigh_bytes_not_counts(self):
        """The review scenario: a cut where ONE huge tensor is live
        must cost more than a cut where TWO small tensors are live —
        size-weighted costs (via the abstract shape pass) get this
        right where live-value counting inverts it."""
        import jax
        from deeplearning4j_tpu.autodiff.samediff import SameDiff
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(2, 4))
        big = sd._op("tile", [x], {"reps": (64, 64)})   # [128, 256]
        shrunk = sd.math.reduce_sum(big, axis=1)        # [128]
        s1 = sd.math.sin(shrunk)
        s2 = sd.math.cos(shrunk)                        # two small live
        both = sd.math.add(s1, s2)
        out = sd.math.reduce_sum(both)
        ops = list(range(len(sd.ops)))
        vals = {"x": jax.numpy.zeros((2, 4), jax.numpy.float32)}
        sizes = sd._value_sizes(vals, ops, jax.random.PRNGKey(0),
                                False)
        assert sizes, "abstract shape pass must not fall back"
        assert sizes[big.name] > sizes[s1.name] * 50
        costs = sd._segment_cut_costs(ops, (out.name,), sizes)
        # cut after `big` (only the huge tensor live) must cost MORE
        # than the cut where s1+s2 (two small values) are live
        i_big_live = 1      # before reduce_sum: big crosses
        i_two_small = 4     # before add: s1+s2 cross
        assert costs[i_big_live] > costs[i_two_small]
