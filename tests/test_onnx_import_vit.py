"""ViT ONNX conformance (round-2 verdict ask #2: "import one modern
conformance model — a ViT exercises LayerNorm/GELU/attention paths").

The model is a real ONNX wire-format graph (patch-embed Conv →
cls-token Concat → pos-embed Add → N× pre-LN transformer blocks with
multi-head attention and GELU MLP → LN → head), hand-encoded with the
in-repo encoder because the torchscript ONNX exporter needs the
``onnx`` package (not in the image).  Ground truth is the SAME
computation in torch CPU sharing the SAME weights."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

from deeplearning4j_tpu.modelimport.onnx import import_onnx  # noqa: E402
from deeplearning4j_tpu.modelimport.onnx.protobuf import (  # noqa: E402
    encode_model, encode_node, encode_value_info)

R = np.random.RandomState(3)

B, IMG, PATCH, D, H, DEPTH, CLASSES = 2, 32, 8, 64, 4, 2, 10
N = (IMG // PATCH) ** 2 + 1            # tokens incl. cls
DH = D // H


def _w(*shape, scale=0.08):
    return (R.randn(*shape) * scale).astype(np.float32)


def _vit_weights():
    w = {"patch_w": _w(D, 3, PATCH, PATCH), "patch_b": _w(D),
         "cls": _w(1, 1, D), "pos": _w(1, N, D),
         "ln_f_g": np.ones(D, np.float32) + _w(D),
         "ln_f_b": _w(D),
         "head_w": _w(D, CLASSES), "head_b": _w(CLASSES)}
    for i in range(DEPTH):
        w.update({
            f"ln1g_{i}": np.ones(D, np.float32) + _w(D),
            f"ln1b_{i}": _w(D),
            f"qkv_w_{i}": _w(D, 3 * D), f"qkv_b_{i}": _w(3 * D),
            f"out_w_{i}": _w(D, D), f"out_b_{i}": _w(D),
            f"ln2g_{i}": np.ones(D, np.float32) + _w(D),
            f"ln2b_{i}": _w(D),
            f"fc1_w_{i}": _w(D, 4 * D), f"fc1_b_{i}": _w(4 * D),
            f"fc2_w_{i}": _w(4 * D, D), f"fc2_b_{i}": _w(D),
        })
    return w


def _vit_nodes():
    """The ONNX graph: returns (nodes, extra_inits)."""
    nodes = []
    inits = {
        "tok_shape": np.asarray([B, D, N - 1], np.int64),
        "heads_shape": np.asarray([B, N, H, DH], np.int64),
        "merge_shape": np.asarray([B, N, D], np.int64),
        "scale": np.asarray(1.0 / np.sqrt(DH), np.float32),
        "cls_idx": np.asarray([0], np.int64),
    }

    def n(op, ins, outs, name, **attrs):
        nodes.append(encode_node(op, ins, outs, name, **attrs))

    # patch embed: Conv → [B, D, 4, 4] → flatten → [B, N-1, D]
    n("Conv", ["x", "patch_w", "patch_b"], ["pe"], "patch",
      strides=[PATCH, PATCH], kernel_shape=[PATCH, PATCH])
    n("Reshape", ["pe", "tok_shape"], ["pe_f"], "pe_flat")
    n("Transpose", ["pe_f"], ["tok"], "pe_t", perm=[0, 2, 1])
    # cls token concat + pos embed (Expand broadcasts over batch)
    inits["cls_shape"] = np.asarray([B, 1, D], np.int64)
    n("Expand", ["cls", "cls_shape"], ["cls_b"], "cls_expand")
    n("Concat", ["cls_b", "tok"], ["seq0"], "cat", axis=1)
    n("Add", ["seq0", "pos"], ["h0"], "pos_add")

    hin = "h0"
    for i in range(DEPTH):
        p = f"b{i}_"
        n("LayerNormalization", [hin, f"ln1g_{i}", f"ln1b_{i}"],
          [p + "ln1"], p + "ln1n", axis=-1)
        n("MatMul", [p + "ln1", f"qkv_w_{i}"], [p + "qkv0"],
          p + "qkvm")
        n("Add", [p + "qkv0", f"qkv_b_{i}"], [p + "qkv"], p + "qkva")
        n("Split", [p + "qkv"], [p + "q", p + "k", p + "v"],
          p + "split", axis=-1, split=[D, D, D])
        for t in ("q", "k", "v"):
            n("Reshape", [p + t, "heads_shape"], [p + t + "h"],
              p + t + "r")
            n("Transpose", [p + t + "h"], [p + t + "t"], p + t + "tp",
              perm=[0, 2, 1, 3])
        n("Transpose", [p + "kt"], [p + "ktt"], p + "ktp2",
          perm=[0, 1, 3, 2])
        n("MatMul", [p + "qt", p + "ktt"], [p + "att0"], p + "attm")
        n("Mul", [p + "att0", "scale"], [p + "att1"], p + "atts")
        n("Softmax", [p + "att1"], [p + "att"], p + "attsm", axis=-1)
        n("MatMul", [p + "att", p + "vt"], [p + "ctx0"], p + "ctxm")
        n("Transpose", [p + "ctx0"], [p + "ctx1"], p + "ctxt",
          perm=[0, 2, 1, 3])
        n("Reshape", [p + "ctx1", "merge_shape"], [p + "ctx"],
          p + "ctxr")
        n("MatMul", [p + "ctx", f"out_w_{i}"], [p + "proj0"],
          p + "projm")
        n("Add", [p + "proj0", f"out_b_{i}"], [p + "proj"],
          p + "proja")
        n("Add", [hin, p + "proj"], [p + "res1"], p + "r1")
        n("LayerNormalization", [p + "res1", f"ln2g_{i}",
                                 f"ln2b_{i}"], [p + "ln2"],
          p + "ln2n", axis=-1)
        n("MatMul", [p + "ln2", f"fc1_w_{i}"], [p + "fc1a"],
          p + "fc1m")
        n("Add", [p + "fc1a", f"fc1_b_{i}"], [p + "fc1"], p + "fc1b")
        n("Gelu", [p + "fc1"], [p + "gelu"], p + "gelun")
        n("MatMul", [p + "gelu", f"fc2_w_{i}"], [p + "fc2a"],
          p + "fc2m")
        n("Add", [p + "fc2a", f"fc2_b_{i}"], [p + "fc2"], p + "fc2b")
        n("Add", [p + "res1", p + "fc2"], [p + "out"], p + "r2")
        hin = p + "out"

    n("LayerNormalization", [hin, "ln_f_g", "ln_f_b"], ["hf"], "lnf",
      axis=-1)
    n("Gather", ["hf", "cls_idx"], ["cls_tok0"], "take_cls", axis=1)
    n("Squeeze", ["cls_tok0"], ["cls_tok"], "sq", axes=[1])
    n("MatMul", ["cls_tok", "head_w"], ["logits0"], "headm")
    n("Add", ["logits0", "head_b"], ["y"], "heada")
    return nodes, inits


def _vit_torch(w, x):
    """The same computation in torch (ground truth)."""
    t = {k: torch.tensor(v) for k, v in w.items()}
    h = F.conv2d(x, t["patch_w"], t["patch_b"], stride=PATCH)
    h = h.flatten(2).transpose(1, 2)
    h = torch.cat([t["cls"].expand(x.shape[0], -1, -1), h], 1)
    h = h + t["pos"]
    for i in range(DEPTH):
        ln1 = F.layer_norm(h, (D,), t[f"ln1g_{i}"], t[f"ln1b_{i}"])
        qkv = ln1 @ t[f"qkv_w_{i}"] + t[f"qkv_b_{i}"]
        q, k, v = qkv.split(D, dim=-1)
        q = q.view(x.shape[0], N, H, DH).transpose(1, 2)
        k = k.view(x.shape[0], N, H, DH).transpose(1, 2)
        v = v.view(x.shape[0], N, H, DH).transpose(1, 2)
        att = (q @ k.transpose(-1, -2)) / np.sqrt(DH)
        ctx = att.softmax(-1) @ v
        ctx = ctx.transpose(1, 2).reshape(x.shape[0], N, D)
        h = h + (ctx @ t[f"out_w_{i}"] + t[f"out_b_{i}"])
        ln2 = F.layer_norm(h, (D,), t[f"ln2g_{i}"], t[f"ln2b_{i}"])
        mid = F.gelu(ln2 @ t[f"fc1_w_{i}"] + t[f"fc1_b_{i}"])
        h = h + (mid @ t[f"fc2_w_{i}"] + t[f"fc2_b_{i}"])
    h = F.layer_norm(h, (D,), t["ln_f_g"], t["ln_f_b"])
    return h[:, 0] @ t["head_w"] + t["head_b"]


class TestViTConformance:
    def test_vit_matches_torch(self):
        weights = _vit_weights()
        nodes, extra = _vit_nodes()
        inits = {**weights, **extra}
        model = encode_model(
            nodes, inits,
            [encode_value_info("x", (B, 3, IMG, IMG))],
            [encode_value_info("y", (B, CLASSES))])
        x = R.randn(B, 3, IMG, IMG).astype(np.float32)
        with torch.no_grad():
            want = _vit_torch(weights, torch.tensor(x)).numpy()
        imp = import_onnx(model)
        got = np.asarray(imp.output({"x": x})[0])
        assert got.shape == (B, CLASSES)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-4)
