"""fit_steps (multi-iteration single-dispatch training) semantics:
N fit() calls and one fit_steps(N) must produce identical parameters,
updater state, and iteration count for deterministic (rng-free) models
(the Keras steps_per_execution analog; SURVEY.md §7 perf work)."""
import jax
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization,
                                               DenseLayer, OutputLayer)
from deeplearning4j_tpu.nn.graph import ComputationGraph


def _net(seed=0):
    g = (NeuralNetConfiguration.Builder()
         .seed(seed).updater(Adam(1e-2))
         .graph_builder()
         .add_inputs("in"))
    g.add_layer("d1", DenseLayer(n_out=8, activation=Activation.RELU),
                "in")
    g.add_layer("bn", BatchNormalization(), "d1")
    g.add_layer("out", OutputLayer(n_out=3,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX), "bn")
    g.set_outputs("out")
    g.set_input_types(InputType.feed_forward(4))
    return ComputationGraph(g.build()).init()


def test_fit_steps_matches_fit_loop():
    rng = np.random.RandomState(0)
    x = rng.randn(32, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
    ds = DataSet(x, y)

    a, b = _net(), _net()
    for _ in range(5):
        a.fit(ds)
    b.fit_steps(ds, 5)

    assert a.iteration_count == b.iteration_count == 5
    fa = jax.tree_util.tree_leaves(a.params)
    fb = jax.tree_util.tree_leaves(b.params)
    for la, lb in zip(fa, fb):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-5, atol=1e-6)
    # BN running stats advanced identically (states threaded in-loop)
    sa = jax.tree_util.tree_leaves(a.states)
    sb = jax.tree_util.tree_leaves(b.states)
    for la, lb in zip(sa, sb):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-5, atol=1e-6)
    # subsequent single-step fits continue from the same point
    a.fit(ds)
    b.fit(ds)
    for la, lb in zip(jax.tree_util.tree_leaves(a.params),
                      jax.tree_util.tree_leaves(b.params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-5, atol=1e-6)


def test_fit_steps_trains():
    rng = np.random.RandomState(1)
    x = rng.randn(64, 4).astype(np.float32)
    ys = (x[:, 0] > 0).astype(int) + (x[:, 1] > 0).astype(int)
    y = np.eye(3, dtype=np.float32)[ys]
    ds = DataSet(x, y)
    net = _net(seed=3)
    net.fit_steps(ds, 2)
    first = float(net.score())
    for _ in range(10):
        net.fit_steps(ds, 10)
    assert float(net.score()) < first * 0.5
    assert net.iteration_count == 102


def test_multilayer_fit_steps_matches_fit_loop():
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)

    def mk():
        conf = (NeuralNetConfiguration.Builder()
                .seed(0).updater(Adam(1e-2))
                .list()
                .layer(DenseLayer(n_out=8, activation=Activation.RELU))
                .layer(BatchNormalization())
                .layer(OutputLayer(n_out=3,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(4))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.RandomState(0)
    x = rng.randn(32, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
    ds = DataSet(x, y)
    a, b = mk(), mk()
    for _ in range(5):
        a.fit(ds)
    b.fit_steps(ds, 5)
    assert a.iteration_count == b.iteration_count == 5
    for la, lb in zip(jax.tree_util.tree_leaves(a.params),
                      jax.tree_util.tree_leaves(b.params)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=1e-5, atol=1e-6)


def test_evaluative_listener_runs_during_training():
    from deeplearning4j_tpu.nn import (MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.optimize.listeners import EvaluativeListener
    conf = (NeuralNetConfiguration.Builder()
            .seed(0).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_out=8, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    x = rng.randn(32, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 32)]
    lis = EvaluativeListener(DataSet(x, y), frequency=2)
    net.set_listeners(lis)
    for _ in range(5):
        net.fit(x, y)
    assert len(lis.evaluations) == 3    # iterations 0, 2, 4
    it, e = lis.evaluations[-1]
    assert 0.0 <= e.accuracy() <= 1.0


def test_top_n_accuracy():
    from deeplearning4j_tpu.evaluation import Evaluation
    labels = np.eye(4)[[0, 1, 2, 3]].astype(float)
    preds = np.asarray([
        [0.6, 0.3, 0.1, 0.0],   # top1 correct
        [0.5, 0.4, 0.1, 0.0],   # top1 wrong, top2 correct
        [0.5, 0.3, 0.1, 0.1],   # top1 wrong, top2 wrong
        [0.1, 0.2, 0.3, 0.4],   # top1 correct
    ])
    e = Evaluation(top_n=2)
    e.eval(labels, preds)
    assert e.accuracy() == pytest.approx(0.5)
    assert e.top_n_accuracy() == pytest.approx(0.75)


def test_fit_steps_rejects_masked_data():
    import pytest
    rng = np.random.RandomState(0)
    x = rng.randn(4, 4).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 4)]
    ds = DataSet(x, y)
    ds.features_mask = np.ones((4, 2), np.float32)
    net = _net()
    with pytest.raises(ValueError, match="mask"):
        net.fit_steps(ds, 2)


def test_stem_space_to_depth_variant_builds():
    """ResNet50 stem_space_to_depth option: same output contract."""
    from deeplearning4j_tpu.models.zoo import ResNet50
    net = ResNet50(num_classes=10, height=32, width=32,
                   stem_space_to_depth=True).init()
    x = np.random.RandomState(0).rand(2, 32, 32, 3).astype(np.float32)
    out = net.output(x)
    arr = np.asarray(out[0] if isinstance(out, (list, tuple)) else out)
    assert arr.shape == (2, 10)
