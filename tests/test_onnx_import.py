"""ONNX import conformance (SURVEY.md S7, test strategy §4.4: run
imported graphs and compare tensors against framework ground truth —
here torch CPU forward passes; fixtures are built with the in-repo
ONNX encoder since this image has no `onnx` package)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from deeplearning4j_tpu.modelimport.onnx import import_onnx
from deeplearning4j_tpu.modelimport.onnx.protobuf import (
    encode_model, encode_node, encode_value_info)


def _mlp_model(m):
    """Sequential(Linear, ReLU, Linear, Softmax) as ONNX bytes."""
    w0 = m[0].weight.detach().numpy()
    b0 = m[0].bias.detach().numpy()
    w1 = m[2].weight.detach().numpy()
    b1 = m[2].bias.detach().numpy()
    nodes = [
        encode_node("Gemm", ["x", "w0", "b0"], ["h0"], "fc1",
                    alpha=1.0, beta=1.0, transB=1),
        encode_node("Relu", ["h0"], ["h1"], "relu"),
        encode_node("Gemm", ["h1", "w1", "b1"], ["h2"], "fc2",
                    alpha=1.0, beta=1.0, transB=1),
        encode_node("Softmax", ["h2"], ["y"], "sm", axis=-1),
    ]
    return encode_model(
        nodes,
        {"w0": w0, "b0": b0, "w1": w1, "b1": b1},
        [encode_value_info("x", (2, 4))],
        [encode_value_info("y", (2, 3))])


class TestMlp:
    def test_matches_torch(self):
        torch.manual_seed(0)
        m = torch.nn.Sequential(torch.nn.Linear(4, 8),
                                torch.nn.ReLU(),
                                torch.nn.Linear(8, 3))
        x = torch.randn(2, 4)
        want = torch.softmax(m(x), -1).detach().numpy()
        imp = import_onnx(_mlp_model(m))
        got = imp.output({"x": x.numpy()})[0]
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)


class TestCnn:
    def _torch_net(self):
        torch.manual_seed(1)
        return torch.nn.Sequential(
            torch.nn.Conv2d(3, 8, 3, stride=1, padding=1),
            torch.nn.BatchNorm2d(8),
            torch.nn.ReLU(),
            torch.nn.MaxPool2d(2, 2),
            torch.nn.Conv2d(8, 16, 3, stride=2, padding=1),
            torch.nn.ReLU(),
            torch.nn.Flatten(),
            torch.nn.Linear(16 * 4 * 4, 5),
        ).eval()

    def _onnx(self, net):
        conv1, bn, _, _, conv2, _, _, fc = net
        bn.eval()
        inits = {
            "w1": conv1.weight.detach().numpy(),
            "c1b": conv1.bias.detach().numpy(),
            "g": bn.weight.detach().numpy(),
            "b": bn.bias.detach().numpy(),
            "rm": bn.running_mean.detach().numpy(),
            "rv": bn.running_var.detach().numpy(),
            "w2": conv2.weight.detach().numpy(),
            "c2b": conv2.bias.detach().numpy(),
            "wf": fc.weight.detach().numpy(),
            "bf": fc.bias.detach().numpy(),
        }
        # Conv bias is rank-1 [C]; as NHWC add it broadcasts over the
        # trailing channel dim directly
        nodes = [
            encode_node("Conv", ["x", "w1", "c1b"], ["a"], "c1",
                        kernel_shape=[3, 3], strides=[1, 1],
                        pads=[1, 1, 1, 1]),
            encode_node("BatchNormalization",
                        ["a", "g", "b", "rm", "rv"], ["bn"], "bn",
                        epsilon=float(bn.eps)),
            encode_node("Relu", ["bn"], ["r1"], "r1"),
            encode_node("MaxPool", ["r1"], ["p1"], "p1",
                        kernel_shape=[2, 2], strides=[2, 2]),
            encode_node("Conv", ["p1", "w2", "c2b"], ["c2o"], "c2",
                        kernel_shape=[3, 3], strides=[2, 2],
                        pads=[1, 1, 1, 1]),
            encode_node("Relu", ["c2o"], ["r2"], "r2"),
            encode_node("Flatten", ["r2"], ["fl"], "fl", axis=1),
            encode_node("Gemm", ["fl", "wf", "bf"], ["y"], "fc",
                        alpha=1.0, beta=1.0, transB=1),
        ]
        return encode_model(
            nodes, inits,
            [encode_value_info("x", (2, 3, 16, 16))],
            [encode_value_info("y", (2, 5))])

    def test_matches_torch(self):
        net = self._torch_net()
        x = torch.randn(2, 3, 16, 16)
        with torch.no_grad():
            want = net(x).numpy()
        imp = import_onnx(self._onnx(net))
        got = imp.output({"x": x.numpy()})[0]
        np.testing.assert_allclose(np.asarray(got), want, atol=2e-4,
                                   rtol=1e-4)


class TestOpCoverage:
    def _run(self, nodes, inits, in_shapes, out_names, feeds):
        model = encode_model(
            nodes, inits,
            [encode_value_info(k, v) for k, v in in_shapes.items()],
            [encode_value_info(o, ()) for o in out_names])
        imp = import_onnx(model)
        return imp.output(feeds, out_names)

    def test_elementwise_chain(self):
        rng = np.random.RandomState(0)
        a = rng.randn(3, 4).astype(np.float32)
        b = rng.randn(3, 4).astype(np.float32)
        nodes = [
            encode_node("Add", ["a", "b"], ["s"], "add"),
            encode_node("Mul", ["s", "a"], ["m"], "mul"),
            encode_node("Sigmoid", ["m"], ["sg"], "sig"),
            encode_node("Clip", ["sg"], ["y"], "clip",
                        min=0.2, max=0.8),
        ]
        [got] = self._run(nodes, {}, {"a": (3, 4), "b": (3, 4)},
                          ["y"], {"a": a, "b": b})
        want = np.clip(1 / (1 + np.exp(-((a + b) * a))), 0.2, 0.8)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)

    def test_shape_ops(self):
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        nodes = [
            encode_node("Transpose", ["x"], ["t"], "tr",
                        perm=[0, 2, 1]),
            encode_node("Reshape", ["t", "shp"], ["r"], "rs"),
            encode_node("Slice", ["r", "st", "en"], ["sl"], "sl"),
            encode_node("Concat", ["sl", "sl"], ["y"], "cc", axis=0),
        ]
        inits = {"shp": np.asarray([4, 6], np.int64),
                 "st": np.asarray([1], np.int64),
                 "en": np.asarray([3], np.int64)}
        [got] = self._run(nodes, inits, {"x": (2, 3, 4)}, ["y"],
                          {"x": x})
        t = np.transpose(x, (0, 2, 1)).reshape(4, 6)
        want = np.concatenate([t[1:3], t[1:3]], 0)
        np.testing.assert_allclose(np.asarray(got), want)

    def test_reductions_and_gather(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        nodes = [
            encode_node("ReduceMean", ["x"], ["rm"], "rm",
                        axes=[1], keepdims=0),
            encode_node("Gather", ["x", "idx"], ["g"], "g", axis=0),
            encode_node("ReduceSum", ["g"], ["rs"], "rs",
                        axes=[0, 1], keepdims=0),
        ]
        inits = {"idx": np.asarray([0, 2], np.int64)}
        rm, rs = self._run(nodes, inits, {"x": (3, 4)},
                           ["rm", "rs"], {"x": x})
        np.testing.assert_allclose(np.asarray(rm), x.mean(1))
        np.testing.assert_allclose(np.asarray(rs),
                                   x[[0, 2]].sum())

    def test_unmapped_op_errors_clearly(self):
        nodes = [encode_node("MadeUpOp", ["x"], ["y"], "nope")]
        with pytest.raises(NotImplementedError, match="MadeUpOp"):
            self._run(nodes, {}, {"x": (2,)}, ["y"],
                      {"x": np.zeros(2, np.float32)})

    def test_global_avg_pool_and_gemm(self):
        torch.manual_seed(2)
        x = torch.randn(2, 6, 5, 5)
        want = torch.nn.functional.adaptive_avg_pool2d(x, 1).numpy()
        nodes = [encode_node("GlobalAveragePool", ["x"], ["y"], "gap")]
        [got] = self._run(nodes, {}, {"x": (2, 6, 5, 5)}, ["y"],
                          {"x": x.numpy()})
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)
