"""Sharded-model serving residency (ISSUE 15): a dense checkpoint
restored onto a virtual 8-device mesh and kept resident sharded
between requests must serve outputs BITWISE equal to the single-chip
dense path, with ~1/N of the dense parameter bytes on each chip.

Runs on the 8-virtual-CPU-device rig (conftest sets
``xla_force_host_platform_device_count=8``); the module is listed in
``_MESH_ONLY_MODULES`` so it is skipped when the flag did not stick.
"""
import threading

import numpy as np
import pytest

from deeplearning4j_tpu.common import telemetry
from deeplearning4j_tpu.common.telemetry import MetricsRegistry
from deeplearning4j_tpu.serving import ModelRegistry, ServingBatcher


@pytest.fixture(autouse=True)
def _fresh_registry():
    MetricsRegistry._reset_for_tests()
    yield
    MetricsRegistry._reset_for_tests()


def _mlp(seed=42):
    """A small MLN whose layer widths divide by tp=2 (16 and 4), so
    the same net exercises dp-only and (dp x tp) residency."""
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn.conf.builders import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=4,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _mesh_1d():
    import jax

    from deeplearning4j_tpu.parallel.mesh import make_mesh
    return make_mesh({"data": 8}, jax.devices()[:8])


def _mesh_2d():
    import jax

    from deeplearning4j_tpu.parallel.mesh import make_mesh
    return make_mesh({"data": 4, "model": 2}, jax.devices()[:8])


def _dense_bytes(params) -> int:
    import jax
    return sum(int(np.prod(leaf.shape, dtype=np.int64) *
                   np.dtype(leaf.dtype).itemsize)
               for leaf in jax.tree_util.tree_leaves(params)
               if hasattr(leaf, "shape"))


# ----------------------------------------------------------------------
class TestShardedServingEquivalence:
    @pytest.mark.parametrize("mode", ["sharded", "fsdp"])
    def test_bitwise_equal_to_dense_and_no_retrace(self, mode):
        """The tentpole acceptance: a dense checkpoint served with
        1/N-sharded residency returns bitwise-identical outputs, and
        post-warmup requests never retrace."""
        net = _mlp()
        rng = np.random.RandomState(0)
        xs = [rng.randn(n, 8).astype(np.float32)
              for n in (1, 3, 8, 11)]
        refs = [np.asarray(net.output(x)) for x in xs]

        reg = ModelRegistry(_mesh_1d(), default_buckets=(8, 16))
        ver = reg.register("m", net, warmup_shape=(8,), mode=mode)
        assert ver.batcher.mode == mode
        assert ver.batcher._serve_params is not None
        for x, ref in zip(xs, refs):
            out = ver.batcher.submit(x).result(timeout=60)
            np.testing.assert_array_equal(out, ref)
        assert reg.retraces_since_warmup("m") == 0
        # the describe() surface carries the residency mode
        assert reg.describe()[0]["versions"][0]["mode"] == mode
        reg.shutdown()

    def test_fsdp_times_tp_on_2d_mesh_bitwise_equal(self):
        """(dp=4 x tp=2): tensor-parallel leaves ride under TP_KEY,
        compute is gathered back to replicated — still bitwise."""
        net = _mlp(seed=7)
        rng = np.random.RandomState(1)
        xs = [rng.randn(n, 8).astype(np.float32) for n in (2, 8, 13)]
        refs = [np.asarray(net.output(x)) for x in xs]

        reg = ModelRegistry(_mesh_2d(), default_buckets=(8, 16))
        ver = reg.register("m2d", net, warmup_shape=(8,),
                           mode="fsdp", tensor_parallel=2)
        # the layout really engaged tp: at least one entry has tp specs
        assert ver.batcher._serve_tp_specs
        for x, ref in zip(xs, refs):
            out = ver.batcher.submit(x).result(timeout=60)
            np.testing.assert_array_equal(out, ref)
        assert reg.retraces_since_warmup("m2d") == 0
        reg.shutdown()

    def test_sharded_mode_on_2d_mesh_defaults_tp_to_model_axis(self):
        """tensor_parallel=None on a (data, model) mesh picks up the
        model-axis extent automatically."""
        net = _mlp(seed=9)
        x = np.random.RandomState(2).randn(4, 8).astype(np.float32)
        ref = np.asarray(net.output(x))
        reg = ModelRegistry(_mesh_2d(), default_buckets=(8,))
        ver = reg.register("auto", net, warmup_shape=(8,),
                           mode="sharded")
        assert ver.batcher._serve_tp_specs
        np.testing.assert_array_equal(
            ver.batcher.submit(x).result(timeout=60), ref)
        reg.shutdown()

    def test_tensor_parallel_must_match_mesh(self):
        net = _mlp()
        b = ServingBatcher(net, buckets=(8,), mesh=_mesh_1d(),
                           mode="sharded", tensor_parallel=3)
        with pytest.raises(ValueError, match="tensor_parallel"):
            b.warmup((8,))
        b.shutdown()


# ----------------------------------------------------------------------
class TestShardedResidency:
    def test_per_chip_residency_is_fraction_of_dense(self):
        """The memory half of the acceptance: what one chip holds
        under sharded residency is ~1/8 of the dense tree (flat-pad
        overhead allowed), surfaced through batcher.params ->
        memory_report and the residency gauge."""
        from deeplearning4j_tpu.common.diagnostics import memory_report
        from deeplearning4j_tpu.serving.residency import \
            resident_param_bytes
        net = _mlp()
        dense = _dense_bytes(net.params)
        reg = ModelRegistry(_mesh_1d(), default_buckets=(8,))
        ver = reg.register("m", net, warmup_shape=(8,), mode="sharded")

        resident = resident_param_bytes(ver.batcher.params)
        assert 0 < resident <= dense / 4, \
            f"resident {resident} not ~1/8 of dense {dense}"
        # ravel-pad keeps it near 1/8, never below the exact shard
        assert resident >= dense / 8

        report = memory_report(model=ver.batcher)
        attr = report["models"]["ServingBatcher"]
        assert attr["params_resident_bytes"] == resident
        # logical bytes stay the full checkpoint size
        assert attr["params_bytes"] >= dense

        g = telemetry.gauge("dl4j_serving_param_resident_bytes")
        assert g.value(model="m", mode="sharded") == resident
        reg.shutdown()

    def test_dense_mode_keeps_model_params_surface(self):
        """mode='dense' leaves batcher.params aliased to the model's
        own tree — no placed layout, no gauge."""
        net = _mlp()
        b = ServingBatcher(net, buckets=(8,))
        assert b._serve_params is None
        assert b.params is net.params
        b.shutdown()

    def test_model_output_stays_dense_after_sharded_serving(self):
        """The sharded layout lives on the batcher, never the model:
        the training-side model.output path is untouched."""
        net = _mlp()
        x = np.random.RandomState(3).randn(5, 8).astype(np.float32)
        ref = np.asarray(net.output(x))
        reg = ModelRegistry(_mesh_1d(), default_buckets=(8,))
        ver = reg.register("m", net, warmup_shape=(8,), mode="fsdp")
        ver.batcher.submit(x).result(timeout=60)
        # model params are still the plain dense tree
        np.testing.assert_array_equal(np.asarray(net.output(x)), ref)
        reg.shutdown()


# ----------------------------------------------------------------------
class TestShardedLifecycle:
    def test_hot_swap_while_sharded_is_hitless(self):
        """Hot-swapping a sharded model under a request stream drops
        nothing: every response matches v1's or v2's dense math."""
        net1, net2 = _mlp(seed=42), _mlp(seed=99)
        x = np.random.RandomState(4).randn(4, 8).astype(np.float32)
        ref1 = np.asarray(net1.output(x))
        ref2 = np.asarray(net2.output(x))
        assert not np.array_equal(ref1, ref2)

        reg = ModelRegistry(_mesh_1d(), default_buckets=(8,))
        reg.register("m", net1, warmup_shape=(8,), mode="sharded")

        results, errors = [], []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    fut = reg.model("m").batcher.submit(x)
                    results.append(np.asarray(fut.result(timeout=60)))
                except Exception as e:      # noqa: BLE001
                    errors.append(e)

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            ver2 = reg.register("m", net2, warmup_shape=(8,),
                                mode="sharded")
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        assert not errors, errors[:3]
        assert results
        for out in results:
            assert (np.array_equal(out, ref1)
                    or np.array_equal(out, ref2))
        # post-swap traffic serves v2, still bitwise, still warm
        np.testing.assert_array_equal(
            ver2.batcher.submit(x).result(timeout=60), ref2)
        assert reg.retraces_since_warmup("m") == 0
        assert telemetry.counter(
            "dl4j_serving_hot_swaps_total").value(model="m") == 1
        reg.shutdown()

    def test_zip_restore_registers_sharded(self, tmp_path):
        """The headline workflow: a dense checkpoint on disk is
        restored straight into sharded residency and serves bitwise."""
        from deeplearning4j_tpu.utils.serializer import ModelSerializer
        net = _mlp(seed=5)
        x = np.random.RandomState(6).randn(6, 8).astype(np.float32)
        ref = np.asarray(net.output(x))
        path = str(tmp_path / "model.zip")
        ModelSerializer.write_model(net, path)

        reg = ModelRegistry(_mesh_1d(), default_buckets=(8,))
        ver = reg.register("restored", path, warmup_shape=(8,),
                           mode="fsdp")
        assert ver.source == path
        assert ver.batcher._serve_params is not None
        np.testing.assert_array_equal(
            ver.batcher.submit(x).result(timeout=60), ref)
        assert reg.retraces_since_warmup("restored") == 0
        reg.shutdown()
