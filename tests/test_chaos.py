"""Chaos harness (ISSUE 11 acceptance): a real SIGTERM mid-epoch in a
REAL subprocess must produce a clean resumable exit (code 75), and
re-running the same command must auto-resume and land on the exact
loss/parameter trajectory of an uninterrupted run — zero manual steps.

The child trains a deterministic MLN through FaultTolerantTrainer; the
DL4J_TPU_CHAOS env var is the only thing the legs vary."""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = textwrap.dedent("""
    import json, sys
    import numpy as np
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.common import faults
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.utils import FaultTolerantTrainer

    ckpt_dir, out_path = sys.argv[1], sys.argv[2]

    def factory():
        conf = (NeuralNetConfiguration.Builder().seed(0)
                .updater(Adam(1e-2)).list()
                .layer(DenseLayer(n_out=8, activation=Activation.RELU))
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.RandomState(0)
    x = rng.randn(48, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    batches = [DataSet(x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8])
               for i in range(6)]

    trainer = FaultTolerantTrainer(factory, ckpt_dir,
                                   save_every_n_iterations=3)
    try:
        trainer.fit(batches, n_epochs=2)
    except faults.TrainingPreempted as e:
        sys.exit(e.exit_code)          # 75: "re-run me to resume"
    m = trainer.model
    leaves = [np.asarray(v).tolist() for v in
              __import__("jax").tree_util.tree_leaves(m.params)]
    with open(out_path, "w") as f:
        json.dump({"iteration_count": m.iteration_count,
                   "epoch_count": m.epoch_count,
                   "score": float(m.score(batches[0])),
                   "params": leaves}, f)
""")


def _run_child(tmp, ckpt_dir, out, chaos=""):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": _ROOT,
           "DL4J_TPU_CHAOS": chaos,
           # keep the child lean and artifact-free
           "DL4J_TPU_FLIGHT_RECORDER": "0",
           "DL4J_TPU_RESUME_BACKOFF": "0.0"}
    script = tmp / "train_child.py"
    if not script.exists():
        script.write_text(_CHILD)
    return subprocess.run(
        [sys.executable, str(script), str(ckpt_dir), str(out)],
        capture_output=True, text=True, timeout=300, cwd=str(tmp),
        env=env)


@pytest.fixture(scope="module")
def baseline(tmp_path_factory):
    """One uninterrupted run: the trajectory every chaos leg must hit."""
    tmp = tmp_path_factory.mktemp("chaos_baseline")
    out = tmp / "final.json"
    r = _run_child(tmp, tmp / "ckpts", out)
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(out.read_text())


def _assert_same_trajectory(final, base):
    assert final["iteration_count"] == base["iteration_count"]
    assert final["epoch_count"] == base["epoch_count"]
    np.testing.assert_allclose(final["score"], base["score"],
                               rtol=1e-6, atol=1e-8)
    for a, b in zip(final["params"], base["params"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_sigterm_mid_epoch_resumes_to_identical_trajectory(
        tmp_path, baseline):
    """kill_after_steps fires a REAL SIGTERM at step 7 (mid epoch 1):
    the run exits 75 after a final snapshot; the identical re-run
    resumes mid-epoch and finishes on the baseline trajectory."""
    ckpts, out = tmp_path / "ckpts", tmp_path / "final.json"
    r1 = _run_child(tmp_path, ckpts, out,
                    chaos="kill_after_steps=7")
    assert r1.returncode == 75, (r1.returncode, r1.stderr[-2000:])
    assert not out.exists()            # the first run never finished
    assert any(p.suffix == ".zip" for p in ckpts.iterdir())
    r2 = _run_child(tmp_path, ckpts, out)          # same command again
    assert r2.returncode == 0, r2.stderr[-2000:]
    _assert_same_trajectory(json.loads(out.read_text()), baseline)


def test_torn_final_checkpoint_falls_back_and_still_matches(
        tmp_path, baseline):
    """torn_checkpoint truncates the preemption snapshot after it is
    written: resume must skip the torn newest file, fall back to the
    last cadence checkpoint, and STILL converge to the baseline (the
    sidecar of the fallback checkpoint keeps the resume exact)."""
    ckpts, out = tmp_path / "ckpts", tmp_path / "final.json"
    r1 = _run_child(tmp_path, ckpts, out,
                    chaos="kill_after_steps=5,torn_checkpoint=1")
    assert r1.returncode == 75, (r1.returncode, r1.stderr[-2000:])
    r2 = _run_child(tmp_path, ckpts, out)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "skipping unreadable checkpoint" in r2.stderr
    _assert_same_trajectory(json.loads(out.read_text()), baseline)
