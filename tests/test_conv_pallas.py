"""Fused conv/BN/ReLU epilogue kernel family (ops/conv_pallas.py,
reference parity: CudnnConvolutionHelper's
cudnnConvolutionBiasActivationForward — SURVEY.md D9).  Off-TPU the
kernels run in Pallas interpret mode, so these exactness and gradient
checks exercise the SAME code path the chip runs — including an f64
leg, which only exists because interpret mode runs on CPU."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.common.environment import Environment
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization, ConvolutionLayer, ConvolutionMode)
from deeplearning4j_tpu.nn.conf.layers_conv_1d3d import (
    Convolution1DLayer, Convolution3D)
from deeplearning4j_tpu.ops import conv_pallas

R = np.random.RandomState(13)


@pytest.fixture
def fused_conv():
    """Force the conv-epilogue family on (the auto heuristic keeps it
    off-CPU off, so the fused path needs the force rung to run under
    tier-1)."""
    env = Environment.get()
    env.extra["fused_conv"] = "1"
    yield
    env.extra.pop("fused_conv", None)


@pytest.fixture
def dense_only():
    env = Environment.get()
    env.extra["fused_conv"] = "0"
    env.extra["fused_bn_bwd"] = "0"
    yield
    env.extra.pop("fused_conv", None)
    env.extra.pop("fused_bn_bwd", None)


def _with_gate(value, fn, *args, **kw):
    env = Environment.get()
    old = env.extra.get("fused_conv")
    env.extra["fused_conv"] = value
    try:
        return fn(*args, **kw)
    finally:
        if old is None:
            env.extra.pop("fused_conv", None)
        else:
            env.extra["fused_conv"] = old


# ---------------------------------------------------------------------------
# building blocks vs their dense formulations
# ---------------------------------------------------------------------------
class TestEpilogueKernel:
    @pytest.mark.parametrize("act", ["relu", "identity"])
    @pytest.mark.parametrize("shape", [(2, 5, 5, 16),   # M=50: ragged
                                       (4, 8, 8, 32),
                                       (40, 24)])       # 2D features
    def test_forward_matches_dense(self, act, shape):
        x = R.randn(*shape).astype(np.float32)
        C = shape[-1]
        s = (1.0 + 0.3 * R.randn(C)).astype(np.float32)
        b = (0.2 * R.randn(C)).astype(np.float32)
        got = conv_pallas.scale_shift_act(x, s, b, act)
        ref = x * s + b
        if act == "relu":
            ref = jax.nn.relu(ref)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("act", ["relu", "identity"])
    def test_gradients_match_autodiff(self, act):
        x = R.randn(2, 5, 5, 16).astype(np.float32)
        s = (1.0 + 0.3 * R.randn(16)).astype(np.float32)
        b = (0.2 * R.randn(16)).astype(np.float32)
        ct = R.randn(*x.shape).astype(np.float32)

        def loss_fused(x, s, b):
            return jnp.sum(conv_pallas.scale_shift_act(x, s, b, act)
                           * ct)

        def loss_ref(x, s, b):
            y = x * s + b
            if act == "relu":
                y = jax.nn.relu(y)
            return jnp.sum(y * ct)

        got = jax.grad(loss_fused, argnums=(0, 1, 2))(x, s, b)
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(x, s, b)
        for g_, w_ in zip(got, want):
            np.testing.assert_allclose(np.asarray(g_), np.asarray(w_),
                                       rtol=2e-4, atol=2e-4)

    def test_gradients_f64(self):
        """Interpret mode exists so f64 gradient checks can exercise
        the chip's code path; central differences at 1e-6 only hold
        in doubles."""
        old = jax.config.read("jax_enable_x64")
        jax.config.update("jax_enable_x64", True)
        try:
            x = R.randn(3, 7, 16).astype(np.float64)
            s = (1.0 + 0.3 * R.randn(16)).astype(np.float64)
            b = (0.2 * R.randn(16)).astype(np.float64)
            ct = R.randn(*x.shape)

            def loss(x, s, b):
                return jnp.sum(
                    conv_pallas.scale_shift_act(x, s, b, "relu") * ct)

            got = jax.grad(loss, argnums=(0, 1, 2))(x, s, b)
            eps = 1e-6
            for i, arg in enumerate((x, s, b)):
                flat = arg.ravel()
                j = int(R.randint(flat.size))
                dv = np.zeros_like(flat)
                dv[j] = eps
                args_p = [x, s, b]
                args_m = [x, s, b]
                args_p[i] = (flat + dv).reshape(arg.shape)
                args_m[i] = (flat - dv).reshape(arg.shape)
                fd = (loss(*args_p) - loss(*args_m)) / (2 * eps)
                np.testing.assert_allclose(
                    np.asarray(got[i]).ravel()[j], float(fd),
                    rtol=1e-6, atol=1e-8)
        finally:
            jax.config.update("jax_enable_x64", old)


class TestChannelStats:
    @pytest.mark.parametrize("shape", [(2, 5, 5, 16), (50, 8),
                                       (3, 4, 4, 4, 8)])
    def test_matches_dense_stats(self, shape):
        x = (R.randn(*shape) * 2 + 0.5).astype(np.float32)
        axes = tuple(range(len(shape) - 1))
        mean, var = conv_pallas.channel_stats(x)
        np.testing.assert_allclose(np.asarray(mean),
                                   x.mean(axis=axes), rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(var), x.var(axis=axes),
                                   rtol=1e-4, atol=1e-5)

    def test_gradients_match_autodiff(self):
        x = R.randn(2, 5, 5, 16).astype(np.float32)
        wm = R.randn(16).astype(np.float32)
        wv = R.randn(16).astype(np.float32)

        def loss_fused(x):
            m, v = conv_pallas.channel_stats(x)
            return jnp.sum(m * wm) + jnp.sum(v * wv)

        def loss_ref(x):
            axes = tuple(range(x.ndim - 1))
            return (jnp.sum(jnp.mean(x, axes) * wm)
                    + jnp.sum(jnp.var(x, axes) * wv))

        np.testing.assert_allclose(
            np.asarray(jax.grad(loss_fused)(x)),
            np.asarray(jax.grad(loss_ref)(x)), rtol=2e-4, atol=2e-4)


class TestMatmulEpilogue:
    @pytest.mark.parametrize("act", ["relu", "identity"])
    @pytest.mark.parametrize("m", [50, 128])          # ragged + exact
    def test_forward_and_grads_match_dense(self, act, m):
        x = (R.randn(m, 128) * 0.5).astype(np.float32)
        w = (R.randn(128, 128) * 0.1).astype(np.float32)
        b = (0.2 * R.randn(128)).astype(np.float32)

        def fused(x, w, b):
            return conv_pallas.matmul_bias_act(x, w, b, act)

        def ref(x, w, b):
            y = x @ w + b
            return jax.nn.relu(y) if act == "relu" else y

        np.testing.assert_allclose(np.asarray(fused(x, w, b)),
                                   np.asarray(ref(x, w, b)),
                                   rtol=2e-5, atol=2e-5)
        got = jax.grad(lambda *a: jnp.sum(fused(*a) ** 2),
                       argnums=(0, 1, 2))(x, w, b)
        want = jax.grad(lambda *a: jnp.sum(ref(*a) ** 2),
                        argnums=(0, 1, 2))(x, w, b)
        for g_, w_ in zip(got, want):
            np.testing.assert_allclose(np.asarray(g_), np.asarray(w_),
                                       rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# layer call sites: fused vs the dense lowering they replace
# ---------------------------------------------------------------------------
class TestConvLayerParity:
    def _layer_parity(self, layer, input_type, x):
        p = layer.init_params(jax.random.PRNGKey(0), input_type)

        def run(params, x):
            y, _ = layer.forward(params, x, training=True)
            return y

        y_dense = _with_gate("0", run, p, x)
        y_fused = _with_gate("1", run, p, x)
        np.testing.assert_allclose(np.asarray(y_fused),
                                   np.asarray(y_dense), rtol=2e-5,
                                   atol=2e-5)

        def loss(params, x):
            return jnp.sum(run(params, x) ** 2)

        gd = _with_gate("0", jax.grad(loss, argnums=(0, 1)), p, x)
        gf = _with_gate("1", jax.grad(loss, argnums=(0, 1)), p, x)
        for leaf_d, leaf_f in zip(jax.tree_util.tree_leaves(gd),
                                  jax.tree_util.tree_leaves(gf)):
            np.testing.assert_allclose(np.asarray(leaf_f),
                                       np.asarray(leaf_d), rtol=2e-4,
                                       atol=2e-4)

    def test_conv2d_bias_relu(self):
        lay = ConvolutionLayer(
            kernel_size=(3, 3), n_in=16, n_out=16,
            convolution_mode=ConvolutionMode.SAME, has_bias=True,
            activation=Activation.RELU)
        self._layer_parity(lay, InputType.convolutional(8, 8, 16),
                           R.randn(2, 8, 8, 16).astype(np.float32))

    def test_conv2d_pointwise_matmul_path(self):
        """1x1 stride-1 convs with MXU-aligned channels take the
        matmul-epilogue kernel — exactness against the dense conv."""
        lay = ConvolutionLayer(
            kernel_size=(1, 1), n_in=128, n_out=128,
            convolution_mode=ConvolutionMode.SAME, has_bias=True,
            activation=Activation.RELU)
        self._layer_parity(lay, InputType.convolutional(4, 4, 128),
                           R.randn(2, 4, 4, 128).astype(np.float32))

    def test_conv1d_routes_through_entry_point(self):
        lay = Convolution1DLayer(
            kernel_size=3, n_in=16, n_out=16,
            convolution_mode=ConvolutionMode.SAME, has_bias=True,
            activation=Activation.RELU)
        self._layer_parity(lay, InputType.recurrent(16, 12),
                           R.randn(2, 12, 16).astype(np.float32))

    def test_conv3d_routes_through_entry_point(self):
        lay = Convolution3D(
            kernel_size=(2, 2, 2), n_in=8, n_out=8,
            convolution_mode=ConvolutionMode.SAME, has_bias=True,
            activation=Activation.RELU)
        self._layer_parity(
            lay, InputType.convolutional_3d(4, 4, 4, 8),
            R.randn(2, 4, 4, 4, 8).astype(np.float32))

    def test_unaligned_channels_fall_back_dense(self, fused_conv):
        """C % 8 != 0 demotes structurally — the layer still works,
        on the dense path."""
        lay = ConvolutionLayer(
            kernel_size=(3, 3), n_in=3, n_out=5,
            convolution_mode=ConvolutionMode.SAME, has_bias=True,
            activation=Activation.RELU)
        p = lay.init_params(jax.random.PRNGKey(0),
                            InputType.convolutional(6, 6, 3))
        x = R.randn(2, 6, 6, 3).astype(np.float32)
        y, _ = lay.forward(p, x, training=True)
        assert y.shape == (2, 6, 6, 5)


class TestBatchNormLayerParity:
    def _bn(self, activation):
        bn = BatchNormalization(activation=activation)
        it = InputType.convolutional(8, 8, 16)
        bn.set_n_in(it, True)
        return (bn, bn.init_params(jax.random.PRNGKey(1), it),
                bn.init_state(it))

    @pytest.mark.parametrize("activation",
                             [Activation.RELU, Activation.IDENTITY,
                              Activation.TANH])
    def test_training_forward_parity(self, activation):
        """Fused stats+normalize(+act) == the dense math; TANH is not
        streamable so only the stats/normalize fuse."""
        bn, p, st = self._bn(activation)
        x = R.randn(4, 8, 8, 16).astype(np.float32)

        def run(p, x):
            y, new_st = bn.forward(p, x, training=True, state=st)
            return y, new_st

        env = Environment.get()
        env.extra["fused_bn_bwd"] = "0"
        try:
            yd, std = _with_gate("0", run, p, x)
            yf, stf = _with_gate("1", run, p, x)
        finally:
            env.extra.pop("fused_bn_bwd", None)
        np.testing.assert_allclose(np.asarray(yf), np.asarray(yd),
                                   rtol=2e-5, atol=2e-5)
        for k in ("mean", "var"):
            np.testing.assert_allclose(np.asarray(stf[k]),
                                       np.asarray(std[k]), rtol=1e-5,
                                       atol=1e-6)

    def test_training_gradients_parity(self):
        bn, p, st = self._bn(Activation.RELU)
        x = R.randn(4, 8, 8, 16).astype(np.float32)

        def loss(p, x):
            y, _ = bn.forward(p, x, training=True, state=st)
            return jnp.sum(y ** 2)

        env = Environment.get()
        env.extra["fused_bn_bwd"] = "0"
        try:
            gd = _with_gate("0", jax.grad(loss, argnums=(0, 1)), p, x)
            gf = _with_gate("1", jax.grad(loss, argnums=(0, 1)), p, x)
        finally:
            env.extra.pop("fused_bn_bwd", None)
        for leaf_d, leaf_f in zip(jax.tree_util.tree_leaves(gd),
                                  jax.tree_util.tree_leaves(gf)):
            np.testing.assert_allclose(np.asarray(leaf_f),
                                       np.asarray(leaf_d), rtol=5e-4,
                                       atol=5e-4)

    def test_composes_with_fused_bn_backward(self):
        """DL4J_TPU_FUSED_CONV stats forward + DL4J_TPU_FUSED_BN_BWD
        backward: the full hand-kernel round trip tracks the dense
        autodiff (the ISSUE-13 'composes with bn_pallas backward'
        claim)."""
        bn, p, st = self._bn(Activation.RELU)
        x = R.randn(4, 8, 8, 16).astype(np.float32)

        def loss(p, x):
            y, _ = bn.forward(p, x, training=True, state=st)
            return jnp.sum(y ** 2)

        env = Environment.get()
        env.extra["fused_bn_bwd"] = "0"
        gd = _with_gate("0", jax.grad(loss, argnums=(0, 1)), p, x)
        env.extra["fused_bn_bwd"] = "1"
        try:
            gc = _with_gate("1", jax.grad(loss, argnums=(0, 1)), p, x)
        finally:
            env.extra.pop("fused_bn_bwd", None)
        for leaf_d, leaf_c in zip(jax.tree_util.tree_leaves(gd),
                                  jax.tree_util.tree_leaves(gc)):
            np.testing.assert_allclose(np.asarray(leaf_c),
                                       np.asarray(leaf_d), rtol=5e-4,
                                       atol=5e-4)

    def test_inference_epilogue_parity(self):
        bn, p, st = self._bn(Activation.RELU)
        st = {"mean": jnp.asarray(0.3 * R.randn(16), jnp.float32),
              "var": jnp.asarray(1 + 0.1 * R.rand(16), jnp.float32)}
        x = R.randn(4, 8, 8, 16).astype(np.float32)

        def run(p, x):
            y, _ = bn.forward(p, x, training=False, state=st)
            return y

        yd = _with_gate("0", run, p, x)
        yf = _with_gate("1", run, p, x)
        np.testing.assert_allclose(np.asarray(yf), np.asarray(yd),
                                   rtol=2e-5, atol=2e-5)


class TestConvForwardVsDenseLowering:
    """The acceptance bar: fused conv+BN+ReLU forward against the raw
    dense lax.conv_general_dilated lowering, end to end."""

    def test_conv_bn_relu_stack(self, fused_conv):
        x = R.randn(2, 8, 8, 16).astype(np.float32)
        w = (0.1 * R.randn(3, 3, 16, 16)).astype(np.float32)
        gamma = (1 + 0.1 * R.randn(16)).astype(np.float32)
        beta = (0.1 * R.randn(16)).astype(np.float32)
        eps = 1e-5

        def fused(x, w, gamma, beta):
            z = conv_pallas.conv_forward(
                x, w, window_strides=(1, 1), padding="SAME",
                rhs_dilation=(1, 1),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                bias=None, activation=Activation.IDENTITY)
            out = conv_pallas.maybe_fused_bn_train(
                z, gamma, beta, eps, Activation.RELU)
            assert out is not None
            return out[0]

        def dense(x, w, gamma, beta):
            z = jax.lax.conv_general_dilated(
                x, w, window_strides=(1, 1), padding="SAME",
                rhs_dilation=(1, 1),
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            axes = (0, 1, 2)
            mean = jnp.mean(z, axes)
            var = jnp.var(z, axes)
            return jax.nn.relu(
                (z - mean) / jnp.sqrt(var + eps) * gamma + beta)

        np.testing.assert_allclose(
            np.asarray(fused(x, w, gamma, beta)),
            np.asarray(dense(x, w, gamma, beta)), rtol=2e-5,
            atol=2e-5)
        got = jax.grad(lambda *a: jnp.sum(fused(*a) ** 2),
                       argnums=(0, 1, 2, 3))(x, w, gamma, beta)
        want = jax.grad(lambda *a: jnp.sum(dense(*a) ** 2),
                        argnums=(0, 1, 2, 3))(x, w, gamma, beta)
        for g_, w_ in zip(got, want):
            np.testing.assert_allclose(np.asarray(g_), np.asarray(w_),
                                       rtol=5e-4, atol=5e-4)
