"""Generative serving engine: sampling primitives, prefill/decode
continuous batching, KV-pool shedding over HTTP, streaming chunked
responses, and residency composition.

Each piece of the ISSUE-16 stack is pinned where an operator would
feel it break: tokens must match the dense full-re-forward reference,
a full pool must shed 429 with a measured Retry-After BEFORE any
chunk is sent, a disconnected client must free its blocks, and a
mid-stream handler exception must terminate the chunk stream as a
truncation the client detects — never a wedged connection.
"""
from __future__ import annotations

import http.client
import json
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.models.decoder import DecoderConfig, DecoderLM
from deeplearning4j_tpu.serving.generative import DecodeEngine
from deeplearning4j_tpu.serving.kvcache import (KVBlockPool,
                                                PoolExhausted)


def _engine(conf=None, *, kv_blocks=64, block=8, prompt_buckets=(16,),
            decode_buckets=(4,), max_seq_len=64, **kw):
    conf = conf or DecoderConfig.tiny()
    model = DecoderLM(conf)
    pool = KVBlockPool(conf.n_layers, kv_blocks, block, conf.n_heads,
                       conf.head_dim, name="t-gen")
    eng = DecodeEngine(model, model.init(), pool, name="t-gen",
                       prompt_buckets=prompt_buckets,
                       decode_buckets=decode_buckets,
                       max_seq_len=max_seq_len, **kw)
    eng.warmup()
    return model, pool, eng


class TestSampling:
    def test_greedy_is_argmax(self):
        import jax
        from deeplearning4j_tpu.ops.sampling import (greedy,
                                                     sample_logits)
        logits = np.random.default_rng(0).normal(size=(4, 16)) \
            .astype(np.float32)
        ids = np.asarray(greedy(logits))
        assert list(ids) == list(np.argmax(logits, axis=-1))
        # temperature 0 through the stochastic path is greedy too
        ids0 = np.asarray(sample_logits(
            logits, jax.random.PRNGKey(1),
            np.zeros((4,), np.float32), np.zeros((4,), np.int32)))
        assert list(ids0) == list(np.argmax(logits, axis=-1))

    def test_same_key_same_sample_deterministic(self):
        import jax
        from deeplearning4j_tpu.ops.sampling import sample_logits
        logits = np.random.default_rng(1).normal(size=(2, 32)) \
            .astype(np.float32)
        a = np.asarray(sample_logits(logits, jax.random.PRNGKey(7),
                                     temperature=1.0))
        b = np.asarray(sample_logits(logits, jax.random.PRNGKey(7),
                                     temperature=1.0))
        assert list(a) == list(b)

    def test_top_k_restricts_support(self):
        import jax
        from deeplearning4j_tpu.ops.sampling import sample_logits
        logits = np.arange(16, dtype=np.float32)[None, :]
        top3 = {13, 14, 15}
        for i in range(20):
            t = int(np.asarray(sample_logits(
                logits, jax.random.PRNGKey(i), temperature=2.0,
                top_k=3))[0])
            assert t in top3

    def test_distribution_tracks_logit_mass(self):
        """~2:1 logit odds must come out ~2:1 empirically (sanity on
        the categorical plumbing, not a statistical proof)."""
        import jax
        from deeplearning4j_tpu.ops.sampling import sample_logits
        logits = np.log(np.array([[2.0, 1.0, 1e-9]], np.float32))
        n = 600
        draws = np.asarray(sample_logits(
            np.repeat(logits, n, 0), jax.random.PRNGKey(0),
            temperature=1.0))
        counts = np.bincount(draws, minlength=3)
        assert counts[2] == 0
        assert 0.5 < counts[0] / max(counts[1], 1) * 0.5 < 2.0


class TestDecodeEngine:
    def test_greedy_decode_matches_dense_reference(self):
        model, pool, eng = _engine()
        prompt = np.array([5, 9, 2, 7])
        got = list(eng.submit(prompt, 8))
        ref = list(model.reference_decode(eng.params, prompt, 8,
                                          eos_id=model.conf.eos_id))
        assert got == ref
        assert eng.retraces_since_warmup() == 0
        eng.shutdown()

    def test_multi_block_generation_chains_and_matches(self):
        """A completion long enough to cross several block
        boundaries — the table-chaining path, checked against the
        no-cache reference."""
        model, pool, eng = _engine(block=4, max_seq_len=48)
        prompt = np.array([3, 11, 29])
        got = list(eng.submit(prompt, 24))
        ref = list(model.reference_decode(eng.params, prompt, 24,
                                          eos_id=model.conf.eos_id))
        assert got == ref
        assert pool.live_blocks == 0        # freed on completion
        eng.shutdown()

    def test_eos_mid_batch_frees_blocks_while_others_decode(self):
        """Pick an eos_id that greedy decode is KNOWN to hit (learned
        from a reference run), then decode it next to a sequence that
        never hits EOS: the early one must leave the batch, free its
        blocks, and not perturb the survivor's tokens."""
        conf = DecoderConfig.tiny()
        probe = DecoderLM(conf)
        ref = list(probe.reference_decode(probe.init(),
                                          np.array([5, 9, 2, 7]), 8))
        eos = ref[3]                        # hit at step 4
        conf2 = DecoderConfig(**{**conf.__dict__, "eos_id": eos})
        model, pool, eng = _engine(conf2, decode_buckets=(4,))
        s1 = eng.submit(np.array([5, 9, 2, 7]), 8)
        s2 = eng.submit(np.array([8, 3]), 8)
        t1 = list(s1)
        t2 = list(s2)
        assert s1.reason == "eos" and t1 == ref[:4]
        assert s2.reason == "max_tokens" and len(t2) == 8
        ref2 = list(model.reference_decode(eng.params,
                                           np.array([8, 3]), 8,
                                           eos_id=eos))
        assert t2 == ref2                   # survivor undisturbed
        assert pool.live_blocks == 0
        assert eng.retraces_since_warmup() == 0
        eng.shutdown()

    def test_cancel_frees_blocks_mid_generation(self):
        model, pool, eng = _engine(decode_buckets=(4,))
        stream = eng.submit(np.array([5, 9, 2, 7]), 2000)
        assert stream.next(timeout=10) is not None
        assert pool.live_blocks > 0
        stream.cancel()
        deadline = time.monotonic() + 10
        while pool.live_blocks and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pool.live_blocks == 0
        assert stream.reason == "cancelled"
        eng.shutdown()

    def test_max_tokens_capped_by_pool_capacity(self):
        """max_tokens silently caps at the engine's max_seq_len so a
        greedy client cannot run a sequence past its block budget."""
        model, pool, eng = _engine(max_seq_len=24, block=8)
        stream = eng.submit(np.array([1, 2, 3, 4]), 10_000)
        toks = list(stream)
        assert len(toks) <= 24 - 4          # the hard capacity cap
        assert stream.reason in ("max_tokens", "eos")
        ref = list(model.reference_decode(eng.params,
                                          np.array([1, 2, 3, 4]),
                                          24 - 4,
                                          eos_id=model.conf.eos_id))
        assert toks == ref                  # capped run still exact
        assert pool.live_blocks == 0
        eng.shutdown()

    def test_submit_sheds_synchronously_when_pool_full(self):
        model, pool, eng = _engine(kv_blocks=3, block=8)  # 2 usable
        s = eng.submit(np.arange(2, 12), 4)               # 2 blocks
        with pytest.raises(PoolExhausted):
            eng.submit(np.arange(2, 12), 4)
        list(s)
        eng.shutdown()

    def test_submit_after_shutdown_restarts_worker(self):
        """Regression (dl4j-lint lock-discipline finding): shutdown
        used to leave ``_worker`` pointing at the joined thread, so a
        later submit enqueued onto a dead queue and its stream hung
        forever. Shutdown now swaps the worker out under the submit
        lock; a post-shutdown submit must see None, start a fresh
        worker, and stream a full, correct completion."""
        model, pool, eng = _engine()
        prompt = np.array([5, 9, 2, 7])
        list(eng.submit(prompt, 4))
        eng.shutdown()
        stream = eng.submit(prompt, 8)
        got = []
        for _ in range(8):
            t = stream.next(timeout=10)
            if t is None:
                break
            got.append(t)
        ref = list(model.reference_decode(eng.params, prompt, 8,
                                          eos_id=model.conf.eos_id))
        assert got == ref
        assert pool.live_blocks == 0
        eng.shutdown()

    def test_shutdown_submit_race_never_strands_stream(self):
        """Hammer shutdown against concurrent submits: every stream a
        submit returns must terminate — served by the old worker
        (drained before shutdown's join returns) or by the fresh one a
        post-shutdown submit starts — never parked on a dead queue."""
        model, pool, eng = _engine()
        prompt = np.array([5, 9, 2])
        streams, errs = [], []

        def submitter():
            for _ in range(6):
                try:
                    streams.append(eng.submit(prompt, 3))
                except PoolExhausted:
                    pass
                except Exception as e:       # noqa: BLE001
                    errs.append(e)

        threads = [threading.Thread(target=submitter)
                   for _ in range(3)]
        for t in threads:
            t.start()
        for _ in range(8):
            eng.shutdown()
            time.sleep(0.005)
        for t in threads:
            t.join()
        assert not errs
        import queue as _queue
        deadline = time.monotonic() + 30
        for s in streams:
            while s.reason is None:
                assert time.monotonic() < deadline, \
                    "stream stranded after shutdown/submit race"
                try:
                    s.next(timeout=0.5)
                except _queue.Empty:
                    pass
        assert sum(1 for s in streams
                   if s.reason in ("max_tokens", "eos")) == len(streams)
        eng.shutdown()
        assert pool.live_blocks == 0


def _mesh_1d():
    import jax

    from deeplearning4j_tpu.parallel.mesh import make_mesh
    return make_mesh({"data": 8}, jax.devices()[:8])


class TestResidencyComposition:
    @pytest.mark.parametrize("mode", ["sharded", "fsdp"])
    def test_sharded_residency_tokens_equal_dense(self, mode):
        """mode="fsdp"/"sharded" on the virtual 8-device mesh must
        stream exactly the dense tokens — the generative version of
        the residency bitwise guarantee."""
        import jax
        if len(jax.devices()) < 8:
            pytest.skip("needs the virtual 8-device mesh")
        from deeplearning4j_tpu.serving.batcher import ServingBatcher
        conf = DecoderConfig.tiny()
        gen_cfg = {"kv_blocks": 32, "kv_block_size": 8,
                   "prompt_buckets": (16,), "decode_buckets": (4,),
                   "max_seq_len": 64}
        dense = ServingBatcher(DecoderLM(conf), buckets=(8,),
                               mesh=None, name="gen-dense",
                               generate=dict(gen_cfg))
        dense.warmup_generate()
        sharded = ServingBatcher(DecoderLM(conf), buckets=(8,),
                                 mesh=_mesh_1d(), name="gen-shard",
                                 mode=mode, generate=dict(gen_cfg))
        sharded.warmup_generate()
        prompt = np.array([5, 9, 2, 7])
        t_dense = list(dense.submit_generate(prompt, 8))
        t_shard = list(sharded.submit_generate(prompt, 8))
        assert t_dense == t_shard
        assert sharded.engine.retraces_since_warmup() == 0
        dense.shutdown()
        sharded.shutdown()


def _serve_generative(**generate_overrides):
    from deeplearning4j_tpu.serving.registry import ModelRegistry
    from deeplearning4j_tpu.serving.server import InferenceServer
    conf = DecoderConfig.tiny()
    gen = {"kv_blocks": 32, "kv_block_size": 8,
           "prompt_buckets": (16,), "decode_buckets": (4,),
           "max_seq_len": 64}
    gen.update(generate_overrides)
    reg = ModelRegistry()
    ver = reg.register("lm", DecoderLM(conf), generate=gen)
    srv = InferenceServer(reg).start(0)
    return reg, ver, srv


def _gen_request(port, body, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    conn.request("POST", "/v1/models/lm:generate",
                 body=json.dumps(body).encode(),
                 headers={"Content-Type": "application/json"})
    return conn, conn.getresponse()


class TestGenerateEndpoint:
    def test_streams_ndjson_tokens_then_done(self):
        reg, ver, srv = _serve_generative()
        try:
            conn, resp = _gen_request(
                srv.port, {"prompt": [5, 9, 2, 7], "max_tokens": 6})
            assert resp.status == 200
            assert resp.getheader("Transfer-Encoding") == "chunked"
            assert resp.getheader("X-Model-Version") == "1"
            lines = [json.loads(ln) for ln in
                     resp.read().decode().strip().splitlines()]
            toks = [r["token"] for r in lines if "token" in r]
            done = lines[-1]
            assert done["done"] and done["tokens"] == len(toks) == 6
            model = ver.model
            ref = list(model.reference_decode(
                ver.batcher.engine.params, np.array([5, 9, 2, 7]), 6,
                eos_id=model.conf.eos_id))
            assert toks == ref
            assert ver.retraces_since_warmup() == 0
            conn.close()
        finally:
            srv.stop()
            reg.shutdown()

    def test_non_stream_mode_buffers_one_json(self):
        reg, ver, srv = _serve_generative()
        try:
            conn, resp = _gen_request(
                srv.port, {"prompt": [5, 9], "max_tokens": 4,
                           "stream": False})
            assert resp.status == 200
            doc = json.loads(resp.read())
            assert len(doc["tokens"]) == 4
            assert doc["reason"] == "max_tokens"
            conn.close()
        finally:
            srv.stop()
            reg.shutdown()

    def test_pool_exhaustion_is_429_with_retry_after(self):
        """A prompt the pool cannot hold must shed BEFORE any chunk:
        a plain 429 carrying a positive integer Retry-After."""
        reg, ver, srv = _serve_generative(kv_blocks=3)   # 2 usable
        pool = ver.batcher.engine.pool
        try:
            # occupy every usable block for the request's lifetime
            # (deterministic: an HTTP holder could finish and free
            # its blocks before the shed request lands)
            pool.alloc("hog", pool.usable_blocks * pool.block_size)
            conn, resp = _gen_request(
                srv.port, {"prompt": list(range(2, 12)),
                           "max_tokens": 4})
            assert resp.status == 429
            retry = resp.getheader("Retry-After")
            assert retry is not None and int(retry) >= 1
            doc = json.loads(resp.read())
            assert doc["reason"] == "kv_pool"
            conn.close()
            pool.free("hog")
        finally:
            srv.stop()
            reg.shutdown()

    def test_client_disconnect_frees_blocks(self):
        reg, ver, srv = _serve_generative()
        pool = ver.batcher.engine.pool
        try:
            conn, resp = _gen_request(
                srv.port, {"prompt": [5, 9, 2, 7],
                           "max_tokens": 2000})
            # read one chunk line, then slam the socket shut
            resp.fp.readline()
            conn.sock.close()
            deadline = time.monotonic() + 15
            while pool.live_blocks and time.monotonic() < deadline:
                time.sleep(0.02)
            assert pool.live_blocks == 0
        finally:
            srv.stop()
            reg.shutdown()

    def test_unknown_model_404_and_non_generative_400(self):
        from deeplearning4j_tpu.serving.registry import ModelRegistry
        from deeplearning4j_tpu.serving.server import InferenceServer

        class Dense:
            def output(self, x):
                return x

        reg = ModelRegistry()
        reg.register("plain", Dense())
        srv = InferenceServer(reg).start(0)
        try:
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=10)
            conn.request("POST", "/v1/models/nope:generate",
                         body=b'{"prompt": [1]}')
            assert conn.getresponse().status == 404
            conn.close()
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=10)
            conn.request("POST", "/v1/models/plain:generate",
                         body=b'{"prompt": [1]}')
            assert conn.getresponse().status == 400
            conn.close()
        finally:
            srv.stop()
            reg.shutdown()


class TestRouterRelay:
    def test_router_relays_token_stream_chunked(self):
        from deeplearning4j_tpu.serving.router import ServingRouter
        conf = DecoderConfig.tiny()
        router = ServingRouter(n_replicas=2).start(0)
        try:
            router.rollout("lm", lambda: DecoderLM(conf), generate={
                "kv_blocks": 32, "kv_block_size": 8,
                "prompt_buckets": (16,), "decode_buckets": (4,),
                "max_seq_len": 64})
            conn = http.client.HTTPConnection("127.0.0.1",
                                              router.port, timeout=60)
            conn.request("POST", "/v1/models/lm:generate",
                         body=json.dumps({"prompt": [5, 9, 2, 7],
                                          "max_tokens": 5}).encode(),
                         headers={"Content-Type":
                                  "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert resp.getheader("Transfer-Encoding") == "chunked"
            lines = [json.loads(ln) for ln in
                     resp.read().decode().strip().splitlines()]
            assert lines[-1]["done"] and lines[-1]["tokens"] == 5
            conn.close()
        finally:
            router.stop()


class TestChunkedHttpUtil:
    def _boom_server(self, n_good_chunks, explode=True):
        """A QuietHandler that streams n chunks then raises (or ends
        cleanly when explode=False)."""
        from deeplearning4j_tpu.common.httputil import (
            QuietHandler, start_http_server)

        class H(QuietHandler):
            def do_GET(self):           # noqa: N802
                self.begin_chunks("text/plain")
                try:
                    for i in range(n_good_chunks):
                        self.send_chunk(f"c{i}\n".encode())
                    if explode:
                        raise RuntimeError("mid-stream failure")
                    self.end_chunks()
                except RuntimeError:
                    self.abort_chunks()

        return start_http_server(H, 0)

    def test_clean_stream_ends_with_terminal_chunk(self):
        httpd, _ = self._boom_server(3, explode=False)
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", httpd.server_address[1], timeout=10)
            conn.request("GET", "/")
            resp = conn.getresponse()
            assert resp.read() == b"c0\nc1\nc2\n"
            conn.close()
        finally:
            httpd.shutdown()
            httpd.server_close()

    def test_mid_stream_exception_truncates_not_wedges(self):
        """The regression: an exception after begin_chunks must
        surface to the client as a PROMPT truncation error — not a
        connection that hangs until timeout."""
        httpd, _ = self._boom_server(2, explode=True)
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", httpd.server_address[1], timeout=10)
            t0 = time.monotonic()
            conn.request("GET", "/")
            resp = conn.getresponse()
            with pytest.raises((http.client.IncompleteRead,
                                http.client.HTTPException, OSError)):
                resp.read()
            assert time.monotonic() - t0 < 8    # no timeout-wedge
            conn.close()
        finally:
            httpd.shutdown()
            httpd.server_close()
