"""Recurrent layer tests (reference: LSTMGradientCheckTests /
GravesLSTMTest / char-RNN example — SURVEY.md 4.5, BASELINE config #3)."""
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.builders import (BackpropType,
                                                 MultiLayerConfiguration)
from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
from deeplearning4j_tpu.nn.conf.layers_recurrent import (
    GRU, LSTM, Bidirectional, BidirectionalMode, EmbeddingSequenceLayer,
    GravesLSTM, LastTimeStepLayer, SimpleRnn)


def _char_data(n=64, t=20, vocab=8, seed=0):
    """Deterministic next-token task: x_{t+1} = (x_t + 1) % vocab."""
    rng = np.random.RandomState(seed)
    starts = rng.randint(0, vocab, size=n)
    seq = (starts[:, None] + np.arange(t + 1)[None, :]) % vocab
    x = np.eye(vocab, dtype=np.float32)[seq[:, :-1]]
    y = np.eye(vocab, dtype=np.float32)[seq[:, 1:]]
    return x, y


def _rnn_conf(layer, vocab=8, tbptt=None):
    b = (NeuralNetConfiguration.Builder()
         .seed(12).updater(Adam(1e-2)).list()
         .layer(layer)
         .layer(RnnOutputLayer(n_out=vocab,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX)))
    if tbptt:
        b = b.backprop_type(BackpropType.TRUNCATED_BPTT) \
             .t_bptt_length(tbptt)
    return b.set_input_type(InputType.recurrent(vocab)).build()


class TestRecurrentLayers:
    @pytest.mark.parametrize("layer_cls", [SimpleRnn, LSTM, GravesLSTM,
                                           GRU])
    def test_char_rnn_learns_next_token(self, layer_cls):
        vocab = 8
        x, y = _char_data(vocab=vocab)
        net = MultiLayerNetwork(
            _rnn_conf(layer_cls(n_out=32), vocab)).init()
        for _ in range(60):
            net.fit(x, y)
        out = np.asarray(net.output(x))
        acc = float(np.mean(out.argmax(-1) == y.argmax(-1)))
        assert acc > 0.95, f"{layer_cls.__name__}: {acc}"

    def test_output_shape(self):
        x, y = _char_data(n=4, t=10)
        net = MultiLayerNetwork(_rnn_conf(LSTM(n_out=16))).init()
        assert net.output(x).shape == (4, 10, 8)

    def test_bidirectional_concat_width(self):
        x, y = _char_data(n=4, t=6)
        conf = _rnn_conf(Bidirectional(fwd=LSTM(n_out=16),
                                       mode=BidirectionalMode.CONCAT))
        net = MultiLayerNetwork(conf).init()
        assert conf.layers[1].n_in == 32  # concat doubles features
        assert net.output(x).shape == (4, 6, 8)

    def test_json_round_trip_recurrent(self):
        conf = _rnn_conf(GravesLSTM(n_out=16))
        back = MultiLayerConfiguration.from_json(conf.to_json())
        assert isinstance(back.layers[0], GravesLSTM)
        assert back.layers[0].n_in == 8
        conf2 = _rnn_conf(Bidirectional(fwd=LSTM(n_out=8)))
        back2 = MultiLayerConfiguration.from_json(conf2.to_json())
        assert isinstance(back2.layers[0], Bidirectional)
        assert isinstance(back2.layers[0].fwd, LSTM)


class TestTbptt:
    def test_tbptt_iterations_and_score(self):
        x, y = _char_data(n=16, t=20)
        net = MultiLayerNetwork(
            _rnn_conf(LSTM(n_out=16), tbptt=5)).init()
        it0 = net.iteration_count
        net.fit(x, y)
        # 20 / 5 = 4 segment updates per batch
        assert net.iteration_count == it0 + 4
        assert np.isfinite(net.score())

    def test_tbptt_state_carry_matters(self):
        """With carry, segment 2 sees segment 1's state: training the
        count-up task with tbptt=2 still converges."""
        x, y = _char_data(n=64, t=16)
        net = MultiLayerNetwork(
            _rnn_conf(LSTM(n_out=32), tbptt=4)).init()
        for _ in range(60):
            net.fit(x, y)
        out = np.asarray(net.output(x))
        acc = float(np.mean(out.argmax(-1) == y.argmax(-1)))
        assert acc > 0.9


class TestRnnTimeStep:
    def test_stream_matches_full_sequence(self):
        x, _ = _char_data(n=4, t=10)
        net = MultiLayerNetwork(_rnn_conf(GravesLSTM(n_out=16))).init()
        full = np.asarray(net.output(x))
        net.rnn_clear_previous_state()
        steps = [np.asarray(net.rnn_time_step(x[:, t]))
                 for t in range(10)]
        stream = np.stack(steps, axis=1)
        np.testing.assert_allclose(stream, full, rtol=1e-4, atol=1e-5)

    def test_clear_resets(self):
        x, _ = _char_data(n=2, t=5)
        net = MultiLayerNetwork(_rnn_conf(LSTM(n_out=8))).init()
        a = np.asarray(net.rnn_time_step(x[:, 0]))
        net.rnn_time_step(x[:, 1])
        net.rnn_clear_previous_state()
        b = np.asarray(net.rnn_time_step(x[:, 0]))
        np.testing.assert_allclose(a, b, rtol=1e-5)
        assert net.rnn_get_previous_state(0) is not None


class TestMasking:
    def test_masked_steps_hold_state_and_output(self):
        x, y = _char_data(n=2, t=6)
        mask = np.ones((2, 6), np.float32)
        mask[:, 3:] = 0.0  # only first 3 steps valid
        net = MultiLayerNetwork(_rnn_conf(LSTM(n_out=8))).init()
        layer = net.conf.layers[0]
        params = net.params["layer_0"]
        out_m, st_m = layer.forward(params, jnp.asarray(x), training=False,
                                    rng=None, state=None,
                                    mask=jnp.asarray(mask))
        out_3, st_3 = layer.forward(params, jnp.asarray(x[:, :3]),
                                    training=False, rng=None, state=None)
        # final state frozen at step 3
        np.testing.assert_allclose(np.asarray(st_m["h"]),
                                   np.asarray(st_3["h"]), rtol=1e-5)

    def test_masked_loss_training(self):
        x, y = _char_data(n=32, t=10)
        mask = np.ones((32, 10), np.float32)
        mask[:, 5:] = 0.0
        net = MultiLayerNetwork(_rnn_conf(LSTM(n_out=16))).init()
        from deeplearning4j_tpu.datasets import DataSet
        ds = DataSet(x, y, features_mask=mask, labels_mask=mask)
        for _ in range(5):
            net.fit(ds)
        assert np.isfinite(net.score())

    def test_last_time_step_layer(self):
        x = np.arange(2 * 4 * 3, dtype=np.float32).reshape(2, 4, 3)
        layer = LastTimeStepLayer()
        out, _ = layer.forward({}, jnp.asarray(x), training=False)
        np.testing.assert_allclose(np.asarray(out), x[:, -1])
        mask = np.asarray([[1, 1, 0, 0], [1, 1, 1, 1]], np.float32)
        out_m, _ = layer.forward({}, jnp.asarray(x), training=False,
                                 mask=jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(out_m)[0], x[0, 1])
        np.testing.assert_allclose(np.asarray(out_m)[1], x[1, 3])

    def test_graph_rnn_state_resets_between_batches(self):
        """Regression: ComputationGraph must not leak batch-sized rnn
        state across fit() calls (crashes on batch-size change)."""
        from deeplearning4j_tpu.nn import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        conf = (NeuralNetConfiguration.Builder()
                .seed(0).updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("in")
                .set_input_types(InputType.recurrent(3))
                .add_layer("rnn", SimpleRnn(n_out=8), "in")
                .add_layer("out", RnnOutputLayer(n_out=3), "rnn")
                .set_outputs("out").build())
        net = ComputationGraph(conf).init()
        x4 = np.random.RandomState(0).rand(4, 5, 3).astype(np.float32)
        y4 = np.eye(3, dtype=np.float32)[
            np.random.RandomState(1).randint(0, 3, (4, 5))]
        net.fit(x4, y4)
        x2, y2 = x4[:2], y4[:2]
        net.fit(x2, y2)  # batch-size change must not crash
        assert net.states["rnn"] == {}  # no state persisted

    def test_embedding_sequence(self):
        tokens = np.random.RandomState(0).randint(0, 10, (4, 6))
        layer = EmbeddingSequenceLayer(n_in=10, n_out=5)
        import jax
        params = layer.init_params(jax.random.PRNGKey(0), None)
        out, _ = layer.forward(params, jnp.asarray(tokens), training=False)
        assert out.shape == (4, 6, 5)
