"""Keras import conformance, modern batch (SURVEY.md D14; round-2
verdict ask #5): ConvLSTM2D, LayerNormalization, MultiHeadAttention,
Conv1DTranspose/Conv3DTranspose, 3D global pooling, custom-layer
registry seam.  Protocol as test_keras_import: build+save with the
in-image Keras, import, compare predictions."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
keras = tf.keras

from deeplearning4j_tpu.modelimport.keras import (  # noqa: E402
    InvalidKerasConfigurationException, KerasModelImport,
    register_keras_layer_mapper)

R = np.random.RandomState(4)


def _compare_sequential(model, x, tmp_path, atol=1e-4):
    path = str(tmp_path / "model.keras")
    model.save(path)
    net = KerasModelImport.import_keras_sequential_model_and_weights(
        path)
    want = np.asarray(model(x, training=False))
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, want, atol=atol, rtol=1e-3)
    return net


def _compare_functional(model, x, tmp_path, atol=1e-4):
    path = str(tmp_path / "model.keras")
    model.save(path)
    net = KerasModelImport.import_keras_model_and_weights(path)
    want = np.asarray(model(x, training=False))
    got = np.asarray(net.output(x))
    np.testing.assert_allclose(got, want, atol=atol, rtol=1e-3)
    return net


class TestLayerNormalization:
    def test_dense_ln(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((10,)),
            keras.layers.Dense(12, activation="relu"),
            keras.layers.LayerNormalization(),
            keras.layers.Dense(4),
        ])
        # non-trivial gamma/beta
        model.layers[1].set_weights([
            (1.0 + 0.3 * R.randn(12)).astype(np.float32),
            (0.2 * R.randn(12)).astype(np.float32)])
        x = R.randn(5, 10).astype(np.float32)
        _compare_sequential(model, x, tmp_path)

    def test_sequence_ln_no_center(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((7, 6)),
            keras.layers.LayerNormalization(center=False),
            keras.layers.Dense(3),
        ])
        model.layers[0].set_weights([
            (1.0 + 0.2 * R.randn(6)).astype(np.float32)])
        x = R.randn(4, 7, 6).astype(np.float32)
        _compare_sequential(model, x, tmp_path)


class TestConvLSTM2D:
    def test_return_sequences_false(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((4, 8, 8, 3)),
            keras.layers.ConvLSTM2D(5, 3, padding="same"),
            keras.layers.GlobalAveragePooling2D(),
            keras.layers.Dense(2),
        ])
        x = (R.randn(2, 4, 8, 8, 3) * 0.5).astype(np.float32)
        _compare_sequential(model, x, tmp_path, atol=3e-4)

    def test_variable_length_time(self, tmp_path):
        """Input((None, h, w, c)) — the canonical ConvLSTM pattern
        (regression: the None time dim misclassified the input as 2D
        convolutional)."""
        model = keras.Sequential([
            keras.layers.Input((None, 6, 6, 2)),
            keras.layers.ConvLSTM2D(3, 3, padding="same"),
            keras.layers.GlobalAveragePooling2D(),
        ])
        x = (R.randn(2, 5, 6, 6, 2) * 0.5).astype(np.float32)
        _compare_sequential(model, x, tmp_path, atol=3e-4)

    def test_variable_length_time_functional(self, tmp_path):
        """Same pattern through the FUNCTIONAL front door (regression:
        the shape heuristic was keyed on the Sequential path's
        first-layer class)."""
        inp = keras.layers.Input((None, 6, 6, 2))
        y = keras.layers.ConvLSTM2D(3, 3, padding="same")(inp)
        y = keras.layers.GlobalAveragePooling2D()(y)
        model = keras.Model(inp, y)
        x = (R.randn(2, 5, 6, 6, 2) * 0.5).astype(np.float32)
        _compare_functional(model, x, tmp_path, atol=3e-4)

    def test_return_sequences_true_strided(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((3, 8, 8, 2)),
            keras.layers.ConvLSTM2D(4, 3, strides=2, padding="valid",
                                    return_sequences=True),
            keras.layers.GlobalAveragePooling3D(),
            keras.layers.Dense(2),
        ])
        x = (R.randn(2, 3, 8, 8, 2) * 0.5).astype(np.float32)
        _compare_sequential(model, x, tmp_path, atol=3e-4)


class TestMultiHeadAttention:
    def test_self_attention(self, tmp_path):
        inp = keras.layers.Input((6, 16))
        y = keras.layers.MultiHeadAttention(
            num_heads=2, key_dim=8, name="mha")(inp, inp)
        y = keras.layers.GlobalAveragePooling1D()(y)
        y = keras.layers.Dense(3)(y)
        model = keras.Model(inp, y)
        x = R.randn(2, 6, 16).astype(np.float32)
        _compare_functional(model, x, tmp_path)

    def test_no_bias(self, tmp_path):
        inp = keras.layers.Input((5, 8))
        y = keras.layers.MultiHeadAttention(
            num_heads=4, key_dim=4, use_bias=False)(inp, inp, inp)
        y = keras.layers.GlobalAveragePooling1D()(y)
        model = keras.Model(inp, y)
        x = R.randn(3, 5, 8).astype(np.float32)
        _compare_functional(model, x, tmp_path)

    def test_value_dim_mismatch_rejected(self, tmp_path):
        """value_dim != key_dim cannot be expressed by the one-head-
        size SelfAttentionLayer; a silent import would leave the
        layer config inconsistent with the loaded Wv/Wo shapes."""
        inp = keras.layers.Input((6, 16))
        y = keras.layers.MultiHeadAttention(
            num_heads=2, key_dim=8, value_dim=4)(inp, inp)
        model = keras.Model(inp, y)
        path = str(tmp_path / "model.keras")
        model.save(path)
        with pytest.raises(InvalidKerasConfigurationException,
                           match="value_dim"):
            KerasModelImport.import_keras_model_and_weights(path)

    def test_value_dim_equal_key_dim_ok(self, tmp_path):
        """An explicit value_dim == key_dim is fine (it IS the
        uniform-head-size form)."""
        inp = keras.layers.Input((6, 16))
        y = keras.layers.MultiHeadAttention(
            num_heads=2, key_dim=8, value_dim=8)(inp, inp)
        y = keras.layers.GlobalAveragePooling1D()(y)
        model = keras.Model(inp, y)
        x = R.randn(2, 6, 16).astype(np.float32)
        _compare_functional(model, x, tmp_path)


class TestGroupNormalization:
    @pytest.mark.parametrize("groups", [2, 1, -1])
    def test_conv_group_norm(self, tmp_path, groups):
        model = keras.Sequential([
            keras.layers.Input((6, 6, 8)),
            keras.layers.Conv2D(8, 3, padding="same"),
            keras.layers.GroupNormalization(groups=groups),
            keras.layers.ReLU(),
        ])
        model.layers[1].set_weights([
            (1.0 + 0.2 * R.randn(8)).astype(np.float32),
            (0.1 * R.randn(8)).astype(np.float32)])
        x = R.randn(2, 6, 6, 8).astype(np.float32)
        _compare_sequential(model, x, tmp_path, atol=3e-4)


class TestUnitNormalization:
    def test_unit_norm(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((9,)),
            keras.layers.Dense(6, activation="tanh"),
            keras.layers.UnitNormalization(),
        ])
        x = R.randn(4, 9).astype(np.float32)
        _compare_sequential(model, x, tmp_path)


class TestConvTranspose1D3D:
    def test_conv1d_transpose(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((10, 4)),
            keras.layers.Conv1DTranspose(6, 3, strides=2,
                                         padding="same",
                                         activation="relu"),
            keras.layers.Conv1DTranspose(2, 3, padding="valid"),
        ])
        x = R.randn(3, 10, 4).astype(np.float32)
        _compare_sequential(model, x, tmp_path)

    def test_conv3d_transpose(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((4, 4, 4, 2)),
            keras.layers.Conv3DTranspose(3, 3, strides=2,
                                         padding="same"),
        ])
        x = R.randn(2, 4, 4, 4, 2).astype(np.float32)
        _compare_sequential(model, x, tmp_path)


class TestCustomLayerSeam:
    def test_register_custom_layer(self, tmp_path):
        """The registerCustomLayer seam: a user-defined Keras layer
        imports through a user-registered mapper."""

        @keras.utils.register_keras_serializable("test")
        class ScaleShift(keras.layers.Layer):
            def __init__(self, factor=2.0, **kw):
                super().__init__(**kw)
                self.factor = factor

            def build(self, input_shape):
                self.shift = self.add_weight(
                    shape=(input_shape[-1],), initializer="zeros",
                    name="shift")

            def call(self, x):
                return x * self.factor + self.shift

            def get_config(self):
                return {**super().get_config(),
                        "factor": self.factor}

        from deeplearning4j_tpu.modelimport.keras.importer import Emit
        from deeplearning4j_tpu.nn.conf.layers import ActivationLayer
        from deeplearning4j_tpu.nn.conf.layers_misc import \
            LayerNormalization  # noqa: F401  (import check only)

        import jax.numpy as jnp
        from deeplearning4j_tpu.nn.conf.layers import Layer
        from dataclasses import dataclass

        @dataclass
        class ScaleShiftLayer(Layer):
            factor: float = 1.0

            def set_n_in(self, input_type, override):
                self.n_in = self.n_out = input_type.size

            def init_params(self, key, input_type, dtype=jnp.float32):
                return {"shift": jnp.zeros((self.n_in,), dtype)}

            def forward(self, params, x, *, training, rng=None,
                        state=None):
                return x * self.factor + params["shift"], state

            def get_output_type(self, input_type):
                return input_type

        @register_keras_layer_mapper("ScaleShift")
        def _map_scale_shift(cfg, bag):
            layer = ScaleShiftLayer(factor=float(cfg["factor"]))
            return [Emit(layer=layer,
                         params={"shift": bag.get(0, "shift")})]

        try:
            model = keras.Sequential([
                keras.layers.Input((6,)),
                keras.layers.Dense(5, activation="tanh"),
                ScaleShift(factor=1.5),
            ])
            model.layers[1].set_weights(
                [(0.3 * R.randn(5)).astype(np.float32)])
            x = R.randn(4, 6).astype(np.float32)
            _compare_sequential(model, x, tmp_path)
        finally:
            from deeplearning4j_tpu.modelimport.keras.importer import \
                KERAS_LAYER_MAP
            KERAS_LAYER_MAP.pop("ScaleShift", None)

    def test_unregistered_custom_layer_fails_loudly(self, tmp_path):
        @keras.utils.register_keras_serializable("test2")
        class Mystery(keras.layers.Layer):
            def call(self, x):
                return x * 2.0

        model = keras.Sequential([
            keras.layers.Input((4,)),
            Mystery(),
        ])
        path = str(tmp_path / "model.keras")
        model.save(path)
        with pytest.raises(InvalidKerasConfigurationException,
                           match="no mapper"):
            KerasModelImport.import_keras_sequential_model_and_weights(
                path)


class TestPreprocessingLayers:
    def test_rescale_resize_augment_head(self, tmp_path):
        """The common exported-vision-model head: Resizing → Rescaling
        → augmentation (inference no-ops) → conv."""
        model = keras.Sequential([
            keras.layers.Input((10, 12, 3)),
            keras.layers.Resizing(8, 8),
            keras.layers.Rescaling(1.0 / 255, offset=-0.5),
            keras.layers.RandomFlip(),
            keras.layers.RandomRotation(0.2),
            keras.layers.ActivityRegularization(l2=0.01),
            keras.layers.Conv2D(4, 3, padding="same"),
        ])
        x = (R.rand(2, 10, 12, 3) * 255).astype(np.float32)
        _compare_sequential(model, x, tmp_path, atol=3e-4)

    def test_per_channel_rescaling(self, tmp_path):
        """Array scale/offset (per-channel ImageNet-style norm) and
        integer pixel inputs promoting to float."""
        model = keras.Sequential([
            keras.layers.Input((4, 4, 3)),
            keras.layers.Rescaling(
                scale=[1 / 0.229, 1 / 0.224, 1 / 0.225],
                offset=[-0.1, 0.2, 0.0]),
        ])
        x = R.rand(2, 4, 4, 3).astype(np.float32)
        net = _compare_sequential(model, x, tmp_path)
        # uint8 pixels must not collapse to zero (weak typing)
        xi = (x * 255).astype(np.uint8)
        out = np.asarray(net.output(xi))
        assert np.abs(out).max() > 1.0

    def test_nearest_resizing(self, tmp_path):
        model = keras.Sequential([
            keras.layers.Input((6, 6, 2)),
            keras.layers.Resizing(12, 9, interpolation="nearest"),
        ])
        x = R.rand(2, 6, 6, 2).astype(np.float32)
        _compare_sequential(model, x, tmp_path)


def test_mapper_count_floor():
    """Registry breadth ratchet (reference has ~60 KerasLayer
    subclasses; SURVEY.md D14)."""
    from deeplearning4j_tpu.modelimport.keras.importer import \
        KERAS_LAYER_MAP
    assert len(KERAS_LAYER_MAP) >= 70, sorted(KERAS_LAYER_MAP)
