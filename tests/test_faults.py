"""Preemption capture, resume policy, chaos parsing, and the two
robustness satellites (ISSUE 11): the StepStatsClient reconnect and the
ParallelInference shutdown future-cancel guarantee."""
import json
import os
import signal
import socket
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.common import faults, telemetry
from deeplearning4j_tpu.common.diagnostics import FlightRecorder
from deeplearning4j_tpu.common.environment import Environment
from deeplearning4j_tpu.common.telemetry import MetricsRegistry


@pytest.fixture(autouse=True)
def _clean_state():
    MetricsRegistry._reset_for_tests()   # also resets faults + guard
    FlightRecorder._reset_for_tests()
    yield
    MetricsRegistry._reset_for_tests()
    FlightRecorder._reset_for_tests()
    Environment.reset()


def _wait(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


# -- preemption capture ------------------------------------------------------
class TestPreemptionGuard:
    def test_sigterm_becomes_flag_and_counter(self):
        guard = faults.install_preemption_capture()
        assert not faults.preemption_requested()
        os.kill(os.getpid(), signal.SIGTERM)
        assert _wait(faults.preemption_requested)
        # the process survived (we are still running) and the notice
        # was counted by reason
        assert telemetry.counter(
            "dl4j_preemption_total", "").value(reason="sigterm") == 1
        guard.clear()
        assert not faults.preemption_requested()

    def test_install_idempotent(self):
        g1 = faults.install_preemption_capture()
        g2 = faults.install_preemption_capture()
        assert g1 is g2

    def test_cooperative_request_without_signal(self):
        faults.PreemptionGuard.get().request("maintenance")
        assert faults.preemption_requested()
        assert telemetry.counter(
            "dl4j_preemption_total", "").value(
                reason="maintenance") == 1

    @pytest.mark.parametrize("guard_first", [True, False],
                             ids=["guard-then-recorder",
                                  "recorder-then-guard"])
    def test_composes_with_flight_recorder_either_order(
            self, guard_first, tmp_path, monkeypatch):
        """Whatever the SIGTERM handler install order, one notice must
        set the flag and the process must SURVIVE to snapshot (the
        recorder's solo fallback re-delivers the signal fatally)."""
        monkeypatch.setenv("DL4J_TPU_FLIGHT_RECORDER", "1")
        monkeypatch.setenv("DL4J_TPU_FLIGHT_RECORDER_DIR", str(tmp_path))
        Environment.reset()
        FlightRecorder._reset_for_tests()
        if guard_first:
            faults.install_preemption_capture()
            FlightRecorder.get().install()
        else:
            FlightRecorder.get().install()
            faults.install_preemption_capture()
        os.kill(os.getpid(), signal.SIGTERM)
        assert _wait(faults.preemption_requested)


# -- resume policy -----------------------------------------------------------
class TestResumePolicy:
    def test_backoff_caps_and_env(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_RESUME_BACKOFF", "2.0")
        monkeypatch.setenv("DL4J_TPU_RESUME_RETRIES", "7")
        Environment.reset()
        assert faults.resume_retries() == 7
        assert faults.resume_backoff(1) == 2.0
        assert faults.resume_backoff(2) == 4.0
        assert faults.resume_backoff(100) == faults.MAX_RESUME_BACKOFF_S

    def test_note_resume_counts_kinds_and_lost_steps(self):
        faults.note_resume("restart")
        faults.note_resume("inprocess", lost_steps=5)
        assert telemetry.counter(
            "dl4j_resume_total", "").value(kind="restart") == 1
        assert telemetry.counter(
            "dl4j_resume_total", "").value(kind="inprocess") == 1
        assert telemetry.counter(
            "dl4j_lost_steps_total", "").value() == 5


# -- chaos monkey ------------------------------------------------------------
class TestChaosMonkey:
    def test_spec_parsing(self):
        cm = faults.ChaosMonkey(
            "kill_after_steps=5, slow_worker=0.25,"
            "torn_checkpoint=1,bogus_directive=3")
        assert cm.kill_after == 5
        assert cm.slow == 0.25
        assert cm.torn is True
        assert cm.hard_kill_after == 0

    def test_env_gate_parsed_once(self, monkeypatch):
        monkeypatch.delenv("DL4J_TPU_CHAOS", raising=False)
        assert faults.chaos_monkey() is None
        faults._reset_for_tests()
        monkeypatch.setenv("DL4J_TPU_CHAOS", "hard_kill_after_steps=9")
        cm = faults.chaos_monkey()
        assert cm is not None and cm.hard_kill_after == 9
        assert faults.chaos_monkey() is cm      # cached

    def test_slow_worker_injects_and_counts(self):
        cm = faults.ChaosMonkey("slow_worker=0.01")
        t0 = time.perf_counter()
        cm.on_step()
        assert time.perf_counter() - t0 >= 0.01
        assert telemetry.counter(
            "dl4j_chaos_injections_total", "").value(
                kind="slow_worker") == 1

    def test_maybe_tear_truncates_newest_once(self, tmp_path):
        cp = tmp_path / "checkpoint_0.zip"
        cp.write_bytes(b"x" * 300)
        cm = faults.ChaosMonkey("torn_checkpoint=1")
        assert cm.maybe_tear(tmp_path)
        assert cp.stat().st_size == 100
        assert not cm.maybe_tear(tmp_path)      # fires once


# -- StepStatsClient reconnect (satellite #2) --------------------------------
class _MiniLeader:
    """A throwaway observatory leader: accepts connections, answers the
    clock handshake, and collects shipped records."""

    def __init__(self, port=0):
        self.srv = socket.socket()
        self.srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.srv.bind(("127.0.0.1", port))
        self.srv.listen(4)
        self.port = self.srv.getsockname()[1]
        self.records = []
        self.conns = []
        self._closing = False
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while not self._closing:
            try:
                conn, _ = self.srv.accept()
            except OSError:
                return
            self.conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            f = conn.makefile("rwb")
            json.loads(f.readline().decode())          # hello
            f.write(json.dumps(
                {"t_leader": time.time()}).encode() + b"\n")
            f.flush()
            f.readline()                               # offset
            for line in f:
                self.records.append(json.loads(line.decode()))
        except (OSError, ValueError):
            pass

    def drop_connections(self):
        for c in self.conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
                c.close()
            except OSError:
                pass
        self.conns = []

    def close(self):
        self._closing = True
        try:
            self.srv.close()
        except OSError:
            pass


class TestStepStatsClientReconnect:
    def test_reconnects_after_leader_drop(self):
        from deeplearning4j_tpu.common.stepstats import StepStatsClient
        leader = _MiniLeader()
        client = StepStatsClient("127.0.0.1", leader.port, worker=0,
                                 reconnect_backoff=0.05)
        try:
            client.ship({"seq": 1})
            assert _wait(lambda: any(r.get("seq") == 1
                                     for r in leader.records))
            # leader drops every connection (e.g. restarted after its
            # own preemption): shipping fails but schedules a retry
            leader.drop_connections()
            deadline = time.monotonic() + 5
            while not client._dead and time.monotonic() < deadline:
                client.ship({"seq": 2})
                time.sleep(0.01)
            assert client._dead        # failure noticed, not fatal
            # ... and the next ships reconnect and deliver again
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not any(
                    r.get("seq") == 3 for r in leader.records):
                client.ship({"seq": 3})
                time.sleep(0.05)
            assert any(r.get("seq") == 3 for r in leader.records)
            assert not client._dead
        finally:
            client.close()
            leader.close()

    def test_reconnect_backoff_is_bounded(self):
        from deeplearning4j_tpu.common.stepstats import StepStatsClient
        leader = _MiniLeader()
        client = StepStatsClient("127.0.0.1", leader.port, worker=0,
                                 reconnect_backoff=0.05, max_backoff=0.2)
        try:
            leader.close()             # nothing to reconnect to
            leader.drop_connections()
            for _ in range(50):
                client.ship({"x": 1})
            # the streak grew but the scheduled delay stays capped
            delay = client._retry_at - time.monotonic()
            assert delay <= 0.2 + 0.05
        finally:
            client.close()

    def test_close_stops_reconnect_attempts(self):
        from deeplearning4j_tpu.common.stepstats import StepStatsClient
        leader = _MiniLeader()
        client = StepStatsClient("127.0.0.1", leader.port, worker=0,
                                 reconnect_backoff=0.0)
        client.close()
        client.ship({"x": 1})          # must not raise or reconnect
        assert client._dead
        leader.close()


# -- ParallelInference shutdown cancel (satellite #1) ------------------------
class TestInferenceShutdownCancel:
    def _net(self):
        from deeplearning4j_tpu.activations import Activation
        from deeplearning4j_tpu.learning import Sgd
        from deeplearning4j_tpu.lossfunctions import LossFunction
        from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                       OutputLayer)
        conf = (NeuralNetConfiguration.Builder().seed(0)
                .updater(Sgd(0.1)).list()
                .layer(DenseLayer(n_out=4, activation=Activation.RELU))
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(8)).build())
        return MultiLayerNetwork(conf).init()

    def test_shutdown_cancels_stranded_futures(self):
        """A request that reaches the queue after the worker died must
        be CANCELLED by shutdown, not stranded forever (ADVICE.md
        round 5: a caller blocking on fut.result() with no timeout
        would otherwise hang)."""
        import concurrent.futures

        from deeplearning4j_tpu.parallel.inference import \
            ParallelInference
        pi = ParallelInference.Builder(self._net()).build()
        with pi._lock:
            pi._ensure_worker()
            worker = pi._worker
            pi._shutdown = True        # worker exits at idle timeout
        assert _wait(lambda: not worker.is_alive())
        # simulate the lost race: an item left behind in the queue of a
        # dead worker (no flag reset — shutdown must not need one)
        fut = concurrent.futures.Future()
        pi._requests.put((np.zeros((1, 8), np.float32), fut,
                          time.monotonic()))
        pi.shutdown()
        assert fut.cancelled()
        with pytest.raises(concurrent.futures.CancelledError):
            fut.result(timeout=0)

    def test_shutdown_then_submit_restarts_service(self):
        from deeplearning4j_tpu.parallel.inference import \
            ParallelInference
        pi = ParallelInference.Builder(self._net()).build()
        x = np.zeros((2, 8), np.float32)
        assert pi.submit(x).result(timeout=60).shape == (2, 2)
        pi.shutdown()
        assert pi.submit(x).result(timeout=60).shape == (2, 2)
        pi.shutdown()


# -- in-process auto-resume (FaultTolerantTrainer) ---------------------------
class TestInProcessAutoResume:
    def _factory(self):
        from deeplearning4j_tpu.activations import Activation
        from deeplearning4j_tpu.learning import Adam
        from deeplearning4j_tpu.lossfunctions import LossFunction
        from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                           NeuralNetConfiguration)
        from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                       OutputLayer)
        conf = (NeuralNetConfiguration.Builder().seed(0)
                .updater(Adam(1e-2)).list()
                .layer(DenseLayer(n_out=8, activation=Activation.RELU))
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    def _batches(self, n=8):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        rng = np.random.RandomState(0)
        x = rng.randn(8 * n, 4).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
        return [DataSet(x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8])
                for i in range(n)]

    def test_transient_failure_resumes_and_finishes(self, monkeypatch,
                                                    tmp_path):
        from deeplearning4j_tpu.optimize.listeners import TrainingListener
        from deeplearning4j_tpu.utils import FaultTolerantTrainer
        monkeypatch.setenv("DL4J_TPU_RESUME_BACKOFF", "0.01")
        Environment.reset()

        class FailOnce(TrainingListener):
            fired = False

            def iteration_done(self, model, iteration, epoch):
                if not FailOnce.fired and iteration >= 5:
                    FailOnce.fired = True
                    raise RuntimeError("injected transient fault")

        t = FaultTolerantTrainer(self._factory, tmp_path,
                                 save_every_n_iterations=4)
        t.add_listeners(FailOnce())
        t.fit(self._batches(), n_epochs=2)
        assert FailOnce.fired
        assert t.model.epoch_count == 2
        assert t.model.iteration_count == 16
        assert telemetry.counter(
            "dl4j_resume_total", "").value(kind="inprocess") == 1
        # the failure hit at iteration 6 with the newest checkpoint at
        # 4: exactly those 2 steps were lost and re-run
        assert telemetry.counter(
            "dl4j_lost_steps_total", "").value() == 2

    def test_retries_exhausted_reraises(self, monkeypatch, tmp_path):
        from deeplearning4j_tpu.optimize.listeners import TrainingListener
        from deeplearning4j_tpu.utils import FaultTolerantTrainer
        monkeypatch.setenv("DL4J_TPU_RESUME_BACKOFF", "0.0")
        monkeypatch.setenv("DL4J_TPU_RESUME_RETRIES", "2")
        Environment.reset()

        class AlwaysFail(TrainingListener):
            calls = 0

            def iteration_done(self, model, iteration, epoch):
                AlwaysFail.calls += 1
                raise RuntimeError("permanent fault")

        t = FaultTolerantTrainer(self._factory, tmp_path)
        t.add_listeners(AlwaysFail())
        with pytest.raises(RuntimeError, match="permanent fault"):
            t.fit(self._batches(2), n_epochs=1)
        assert AlwaysFail.calls == 3       # initial + 2 retries

    def test_cooperative_preemption_snapshots_and_resumes_mid_epoch(
            self, tmp_path):
        """request() mid-epoch → final checkpoint + TrainingPreempted
        (exit code 75); a NEW trainer resumes mid-epoch via the meta
        sidecar and finishes with exactly the full batch count — no
        batch retrained, none skipped."""
        from deeplearning4j_tpu.optimize.listeners import TrainingListener
        from deeplearning4j_tpu.utils import FaultTolerantTrainer

        class PreemptAt(TrainingListener):
            def iteration_done(self, model, iteration, epoch):
                # iteration is 0-based: this is the 4th batch
                if iteration == 3:
                    faults.PreemptionGuard.get().request("test")

        batches = self._batches()
        t1 = FaultTolerantTrainer(self._factory, tmp_path)
        t1.add_listeners(PreemptAt())
        with pytest.raises(faults.TrainingPreempted) as ei:
            t1.fit(batches, n_epochs=1)
        assert ei.value.exit_code == faults.PREEMPTED_EXIT_CODE
        assert t1.model.iteration_count == 4
        faults.PreemptionGuard.get().clear()

        t2 = FaultTolerantTrainer(self._factory, tmp_path)
        assert t2.resumed
        assert t2.model.iteration_count == 4
        assert t2._skip_batches == 4       # sidecar: mid-epoch offset
        t2.fit(batches, n_epochs=1)
        assert t2.model.iteration_count == len(batches)
        assert t2.model.epoch_count == 1
