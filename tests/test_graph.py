"""ComputationGraph + zoo tests (reference: ComputationGraphTest /
TestComputationGraphNetwork and zoo instantiation tests, SURVEY.md 4.8)."""
import numpy as np
import pytest

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam, Sgd
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_conf import \
    ComputationGraphConfiguration
from deeplearning4j_tpu.nn.conf.graph_vertices import (ElementWiseVertex,
                                                       L2NormalizeVertex,
                                                       MergeVertex,
                                                       ScaleVertex,
                                                       SubsetVertex)
from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                               BatchNormalization,
                                               ConvolutionLayer,
                                               DenseLayer,
                                               GlobalPoolingLayer,
                                               OutputLayer)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.utils import ModelSerializer


def _simple_graph_conf():
    return (NeuralNetConfiguration.Builder()
            .seed(7).updater(Adam(1e-2))
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("d1", DenseLayer(n_out=16,
                                        activation=Activation.RELU), "in")
            .add_layer("d2", DenseLayer(n_out=16,
                                        activation=Activation.TANH), "in")
            .add_vertex("merge", MergeVertex(), "d1", "d2")
            .add_layer("out", OutputLayer(n_out=3), "merge")
            .set_outputs("out")
            .build())


def _toy(n=256, seed=0):
    rng = np.random.RandomState(seed)
    centers = np.array([[2, 0, 0, 0], [0, 2, 0, 0], [0, 0, 2, 0]],
                       dtype=np.float32)
    ys = rng.randint(0, 3, size=n)
    xs = centers[ys] + 0.3 * rng.randn(n, 4).astype(np.float32)
    return xs, np.eye(3, dtype=np.float32)[ys], ys


class TestGraphConfig:
    def test_topo_and_shapes(self):
        conf = _simple_graph_conf()
        order = conf.topo_order()
        assert order.index("merge") > order.index("d1")
        assert order.index("out") > order.index("merge")
        assert conf.vertices["out"].content.n_in == 32  # 16+16 merged

    def test_json_round_trip(self):
        conf = _simple_graph_conf()
        js = conf.to_json()
        back = ComputationGraphConfiguration.from_json(js)
        assert back.network_outputs == ["out"]
        assert back.vertices["out"].content.n_in == 32
        assert isinstance(back.vertices["merge"].content, MergeVertex)
        assert back.to_json() == js

    def test_cycle_detection(self):
        conf = _simple_graph_conf()
        conf.vertices["d1"].inputs = ["out"]  # introduce cycle
        with pytest.raises(ValueError, match="cycle"):
            conf.topo_order()


class TestGraphTraining:
    def test_merge_graph_converges(self):
        xs, labels, ys = _toy()
        net = ComputationGraph(_simple_graph_conf()).init()
        for _ in range(40):
            net.fit(xs, labels)
        acc = float(np.mean(net.predict(xs) == ys))
        assert acc > 0.9

    def test_residual_elementwise_add(self):
        xs, labels, ys = _toy()
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("in")
                .set_input_types(InputType.feed_forward(4))
                .add_layer("d1", DenseLayer(n_out=4,
                                            activation=Activation.RELU),
                           "in")
                .add_vertex("res", ElementWiseVertex(
                    ElementWiseVertex.Op.Add), "d1", "in")
                .add_layer("out", OutputLayer(n_out=3), "res")
                .set_outputs("out")
                .build())
        net = ComputationGraph(conf).init()
        for _ in range(40):
            net.fit(xs, labels)
        assert float(np.mean(net.predict(xs) == ys)) > 0.85

    def test_multi_output(self):
        xs, labels, ys = _toy(64)
        reg_targets = xs.sum(-1, keepdims=True)
        conf = (NeuralNetConfiguration.Builder()
                .seed(2).updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("in")
                .set_input_types(InputType.feed_forward(4))
                .add_layer("trunk", DenseLayer(
                    n_out=16, activation=Activation.RELU), "in")
                .add_layer("cls", OutputLayer(n_out=3), "trunk")
                .add_layer("reg", OutputLayer(
                    n_out=1, loss_function=LossFunction.MSE,
                    activation=Activation.IDENTITY), "trunk")
                .set_outputs("cls", "reg")
                .build())
        net = ComputationGraph(conf).init()
        for _ in range(30):
            net.fit([xs], [labels, reg_targets])
        out_cls, out_reg = net.output(xs)
        assert out_cls.shape == (64, 3)
        assert out_reg.shape == (64, 1)
        # regression head learned something
        mse = float(np.mean((np.asarray(out_reg) - reg_targets) ** 2))
        assert mse < np.var(reg_targets)

    def test_vertices_forward(self):
        import jax.numpy as jnp
        x = jnp.asarray([[3.0, 4.0]])
        assert float(ScaleVertex(2.0).forward([x])[0, 0]) == 6.0
        n = L2NormalizeVertex().forward([x])
        np.testing.assert_allclose(np.asarray(n), [[0.6, 0.8]], rtol=1e-5)
        s = SubsetVertex(1, 1).forward([x])
        assert s.shape == (1, 1)

    def test_graph_serialization_round_trip(self, tmp_path):
        xs, labels, _ = _toy(32)
        net = ComputationGraph(_simple_graph_conf()).init()
        net.fit(xs, labels)
        p = tmp_path / "graph.zip"
        ModelSerializer.write_model(net, p)
        back = ModelSerializer.restore_computation_graph(p)
        np.testing.assert_allclose(np.asarray(net.output(xs)),
                                   np.asarray(back.output(xs)),
                                   rtol=1e-5, atol=1e-6)


class TestZoo:
    def test_lenet_builds_and_outputs(self):
        from deeplearning4j_tpu.models import LeNet
        net = LeNet(num_classes=10).init()
        x = np.random.RandomState(0).rand(2, 784).astype(np.float32)
        out = net.output(x)
        assert out.shape == (2, 10)

    def test_simple_cnn(self):
        from deeplearning4j_tpu.models import SimpleCNN
        net = SimpleCNN(num_classes=5, height=16, width=16,
                        channels=3).init()
        x = np.random.RandomState(0).rand(2, 16, 16, 3).astype(np.float32)
        assert net.output(x).shape == (2, 5)

    def test_resnet50_structure(self):
        from deeplearning4j_tpu.models import ResNet50
        net = ResNet50(num_classes=10, height=32, width=32,
                       channels=3).init()
        # 3+4+6+3 = 16 bottleneck blocks, each with an add vertex
        adds = [n for n in net.conf.vertices if n.endswith("_add")]
        assert len(adds) == 16
        # ~23.5M params at 1000 classes; at 10 classes ~ 23.5M - 2M
        n = net.num_params()
        assert 20_000_000 < n < 30_000_000
        x = np.random.RandomState(0).rand(1, 32, 32, 3).astype(np.float32)
        out = net.output(x)
        assert out.shape == (1, 10)

    def test_resnet50_trains_a_step(self):
        from deeplearning4j_tpu.models import ResNet50
        from deeplearning4j_tpu.learning import Sgd
        net = ResNet50(num_classes=4, height=32, width=32, channels=3,
                       updater=Sgd(0.01)).init()
        x = np.random.RandomState(0).rand(4, 32, 32, 3).astype(np.float32)
        y = np.eye(4, dtype=np.float32)
        net.fit(x, y)
        assert np.isfinite(net.score())


class TestGraphRnnTimeStep:
    """Stateful streaming inference on DAG models (SURVEY.md D3/5.7;
    reference: ComputationGraph.rnnTimeStep — round-3 verdict ask #4)."""

    @staticmethod
    def _rnn_graph_conf(vocab=8):
        from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
        from deeplearning4j_tpu.nn.conf.layers_recurrent import (
            GRU, LSTM)
        from deeplearning4j_tpu.lossfunctions import LossFunction
        return (NeuralNetConfiguration.Builder()
                .seed(3).updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("in")
                .set_input_types(InputType.recurrent(vocab))
                .add_layer("lstm", LSTM(n_out=16), "in")
                .add_layer("gru", GRU(n_out=16), "in")
                .add_vertex("merge", MergeVertex(), "lstm", "gru")
                .add_layer("out", RnnOutputLayer(
                    n_out=vocab,
                    loss_function=LossFunction.MCXENT,
                    activation=Activation.SOFTMAX), "merge")
                .set_outputs("out")
                .build())

    @staticmethod
    def _seq(n=4, t=10, vocab=8, seed=0):
        rng = np.random.RandomState(seed)
        seq = rng.randint(0, vocab, size=(n, t))
        return np.eye(vocab, dtype=np.float32)[seq]

    def test_stream_matches_full_sequence(self):
        """A recurrent DAG (LSTM + GRU branches merged) streamed one
        step at a time matches the full-sequence output()."""
        x = self._seq()
        net = ComputationGraph(self._rnn_graph_conf()).init()
        full = np.asarray(net.output(x))
        net.rnn_clear_previous_state()
        steps = [np.asarray(net.rnn_time_step(x[:, t]))
                 for t in range(x.shape[1])]
        stream = np.stack(steps, axis=1)
        np.testing.assert_allclose(stream, full, rtol=1e-4, atol=1e-5)

    def test_chunked_stream_matches(self):
        """3D chunks carry state across calls too (reference:
        rnnTimeStep accepts [b, f, t>1])."""
        x = self._seq(t=12)
        net = ComputationGraph(self._rnn_graph_conf()).init()
        full = np.asarray(net.output(x))
        net.rnn_clear_previous_state()
        parts = [np.asarray(net.rnn_time_step(x[:, t0:t0 + 4]))
                 for t0 in (0, 4, 8)]
        np.testing.assert_allclose(np.concatenate(parts, axis=1),
                                   full, rtol=1e-4, atol=1e-5)

    def test_clear_resets_and_state_roundtrip(self):
        x = self._seq(n=2, t=5)
        net = ComputationGraph(self._rnn_graph_conf()).init()
        a = np.asarray(net.rnn_time_step(x[:, 0]))
        st = net.rnn_get_previous_state("lstm")
        assert st is not None and "h" in st
        net.rnn_time_step(x[:, 1])
        net.rnn_clear_previous_state()
        b = np.asarray(net.rnn_time_step(x[:, 0]))
        np.testing.assert_allclose(a, b, rtol=1e-5)
        # set_previous_state replays from a snapshot
        net.rnn_clear_previous_state()
        net.rnn_time_step(x[:, 0])
        want = np.asarray(net.rnn_time_step(x[:, 1]))
        net.rnn_clear_previous_state()
        net.rnn_time_step(x[:, 0])
        for name in ("lstm", "gru"):
            net.rnn_set_previous_state(
                name, net.rnn_get_previous_state(name))
        got = np.asarray(net.rnn_time_step(x[:, 1]))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_batch_size_mismatch_raises(self):
        x = self._seq(n=4, t=3)
        net = ComputationGraph(self._rnn_graph_conf()).init()
        net.rnn_time_step(x[:, 0])
        with pytest.raises(ValueError, match="batch size"):
            net.rnn_time_step(x[:2, 1])

    def test_bidirectional_rejected(self):
        from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
        from deeplearning4j_tpu.nn.conf.layers_recurrent import (
            LSTM, Bidirectional)
        from deeplearning4j_tpu.lossfunctions import LossFunction
        conf = (NeuralNetConfiguration.Builder()
                .seed(3).updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("in")
                .set_input_types(InputType.recurrent(8))
                .add_layer("bi", Bidirectional(fwd=LSTM(n_out=8)), "in")
                .add_layer("out", RnnOutputLayer(
                    n_out=8, loss_function=LossFunction.MCXENT,
                    activation=Activation.SOFTMAX), "bi")
                .set_outputs("out")
                .build())
        net = ComputationGraph(conf).init()
        with pytest.raises(ValueError, match="Bidirectional"):
            net.rnn_time_step(self._seq(n=2, t=1)[:, 0])

    def test_mixed_recurrent_and_static_inputs(self):
        """A DAG with a recurrent input AND a genuinely feed-forward
        input: only the recurrent input gets the step-dim expansion;
        the static input passes through 2D exactly as in output()."""
        from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
        from deeplearning4j_tpu.nn.conf.layers_recurrent import (
            LSTM, LastTimeStepLayer)
        from deeplearning4j_tpu.lossfunctions import LossFunction
        conf = (NeuralNetConfiguration.Builder()
                .seed(5).updater(Adam(1e-2))
                .graph_builder()
                .add_inputs("seq", "static")
                .set_input_types(InputType.recurrent(8),
                                 InputType.feed_forward(4))
                .add_layer("lstm", LSTM(n_out=16), "seq")
                .add_layer("last", LastTimeStepLayer(), "lstm")
                .add_vertex("merge", MergeVertex(), "last", "static")
                .add_layer("out", OutputLayer(n_out=3), "merge")
                .set_outputs("out")
                .build())
        net = ComputationGraph(conf).init()
        x = self._seq(n=4, t=6)
        rng = np.random.RandomState(1)
        static = rng.randn(4, 4).astype(np.float32)
        full = np.asarray(net.output(x, static))
        net.rnn_clear_previous_state()
        for t in range(6):
            got = np.asarray(net.rnn_time_step(x[:, t], static))
        assert got.ndim == 2
        np.testing.assert_allclose(got, full, rtol=1e-4, atol=1e-5)
