"""CenterLossOutputLayer / OCNNOutputLayer / capsule layer tests
(reference test style: CenterLossOutputLayerTest, OCNNOutputLayerTest,
CapsNetMNISTTest, SURVEY.md §4.8)."""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.learning import Adam
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                   NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.layers_capsule import (
    CapsuleLayer, CapsuleStrengthLayer, PrimaryCapsules)
from deeplearning4j_tpu.nn.conf.layers_output_extra import (
    CenterLossOutputLayer, OCNNOutputLayer)


def _blobs(n=200, d=4, seed=0):
    rng = np.random.RandomState(seed)
    ys = rng.randint(0, 3, n)
    centers = np.eye(3, dtype=np.float32)[:, :3] * 3.0
    centers = np.concatenate([centers, np.zeros((3, d - 3), np.float32)],
                             axis=1)
    xs = centers[ys] + 0.3 * rng.randn(n, d).astype(np.float32)
    return xs, np.eye(3, dtype=np.float32)[ys], ys


class TestCenterLoss:
    def _net(self, lam):
        conf = (NeuralNetConfiguration.Builder()
                .seed(0).updater(Adam(5e-2))
                .list()
                .layer(DenseLayer(n_out=8, activation=Activation.RELU))
                .layer(CenterLossOutputLayer(
                    n_out=3, lambda_=lam,
                    loss_function=LossFunction.MCXENT,
                    activation=Activation.SOFTMAX))
                .set_input_type(InputType.feed_forward(4))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_trains_and_centers_move(self):
        xs, labels, ys = _blobs()
        net = self._net(lam=0.5)
        c0 = np.asarray(net.params["layer_1"]["centers"]).copy()
        for _ in range(80):
            net.fit(xs, labels)
        c1 = np.asarray(net.params["layer_1"]["centers"])
        acc = (np.asarray(net.output(xs)).argmax(-1) == ys).mean()
        assert acc > 0.9
        assert np.abs(c1 - c0).sum() > 0.1   # centers learned

    def test_center_term_tightens_clusters(self):
        """With a large lambda the per-class feature scatter around its
        center shrinks vs lambda=0."""
        xs, labels, ys = _blobs()

        def scatter(lam):
            net = self._net(lam)
            for _ in range(80):
                net.fit(xs, labels)
            # penultimate features
            h = np.asarray(jnp.maximum(
                jnp.asarray(xs) @ net.params["layer_0"]["W"] +
                net.params["layer_0"]["b"], 0))
            tot = 0.0
            for c in range(3):
                f = h[ys == c]
                tot += float(((f - f.mean(0)) ** 2).sum(-1).mean())
            return tot

        assert scatter(2.0) < scatter(0.0)

    def test_output_shape_is_class_probs(self):
        xs, labels, _ = _blobs(n=16)
        net = self._net(lam=0.1)
        out = np.asarray(net.output(xs))
        assert out.shape == (16, 3)
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


class TestOCNN:
    def test_anomaly_scoring(self):
        # OC-NN separates the inlier cluster from the origin: inliers sit
        # away from 0, anomalies near/behind it (the paper's geometry).
        rng = np.random.RandomState(0)
        inliers = (rng.randn(256, 4).astype(np.float32) * 0.4 +
                   np.array([2, 2, 2, 2], np.float32))
        outliers = rng.randn(64, 4).astype(np.float32) * 0.4 - \
            np.array([1, 1, 1, 1], np.float32)
        conf = (NeuralNetConfiguration.Builder()
                .seed(0).updater(Adam(1e-2))
                .list()
                .layer(OCNNOutputLayer(hidden_size=8, nu=0.1,
                                       activation=Activation.RELU))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        dummy = np.zeros((inliers.shape[0], 1), np.float32)
        for _ in range(200):
            net.fit(inliers, dummy)
        s_in = np.asarray(net.output(inliers)).ravel()
        s_out = np.asarray(net.output(outliers)).ravel()
        # inliers score above outliers; most inliers non-negative
        assert np.median(s_in) > np.median(s_out)
        assert (s_in >= 0).mean() > 0.7


class TestCapsules:
    def test_shapes_end_to_end(self):
        conf = (NeuralNetConfiguration.Builder()
                .seed(0).updater(Adam(1e-3))
                .list()
                .layer(PrimaryCapsules(capsule_dimensions=4, channels=2,
                                       kernel_size=(3, 3), stride=(2, 2)))
                .layer(CapsuleLayer(capsules=5, capsule_dimensions=6,
                                    routings=2))
                .layer(CapsuleStrengthLayer())
                .layer(OutputLayer(n_out=5,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.convolutional(9, 9, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.RandomState(0).rand(2, 9, 9, 1).astype(np.float32)
        out = np.asarray(net.output(x))
        assert out.shape == (2, 5)
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)

    def test_squash_bounds_norms(self):
        from deeplearning4j_tpu.nn.conf.layers_capsule import _squash
        v = _squash(jnp.array([[100.0, 0.0], [0.01, 0.0]]))
        n = np.asarray(jnp.linalg.norm(v, axis=-1))
        assert n[0] < 1.0
        assert n[1] < 0.01

    def test_capsnet_learns_toy_task(self):
        """Tiny capsnet separates two simple 2-class images (vertical vs
        horizontal bar)."""
        rng = np.random.RandomState(0)
        n = 64
        xs = np.zeros((n, 8, 8, 1), np.float32)
        ys = rng.randint(0, 2, n)
        for i, y in enumerate(ys):
            pos = rng.randint(1, 7)
            if y == 0:
                xs[i, :, pos, 0] = 1.0
            else:
                xs[i, pos, :, 0] = 1.0
        xs += 0.05 * rng.randn(*xs.shape).astype(np.float32)
        labels = np.eye(2, dtype=np.float32)[ys]
        conf = (NeuralNetConfiguration.Builder()
                .seed(1).updater(Adam(5e-3))
                .list()
                .layer(PrimaryCapsules(capsule_dimensions=4, channels=2,
                                       kernel_size=(3, 3), stride=(2, 2)))
                .layer(CapsuleLayer(capsules=4, capsule_dimensions=4,
                                    routings=2))
                .layer(CapsuleStrengthLayer())
                .layer(OutputLayer(n_out=2,
                                   loss_function=LossFunction.MCXENT,
                                   activation=Activation.SOFTMAX))
                .set_input_type(InputType.convolutional(8, 8, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        for _ in range(80):
            net.fit(xs, labels)
        acc = (np.asarray(net.output(xs)).argmax(-1) == ys).mean()
        assert acc > 0.9
