"""ONNX import conformance, batch 3 (round-2 verdict op-gap closure):
ConvTranspose full attribute surface (grouped / dilated /
output_padding / asymmetric pads / auto_pad / output_shape), TopK
smallest + non-last axis, CumSum exclusive/reverse, non-last-axis
LayerNormalization.  Fixtures hand-encoded with the in-repo ONNX
encoder; ground truth from torch CPU."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

from deeplearning4j_tpu.modelimport.onnx import import_onnx  # noqa: E402
from deeplearning4j_tpu.modelimport.onnx.protobuf import (  # noqa: E402
    encode_model, encode_node, encode_value_info)

R = np.random.RandomState(7)


def _run(nodes, inits, in_specs, out_specs, feeds):
    model = encode_model(nodes, inits,
                         [encode_value_info(n, s) for n, s in in_specs],
                         [encode_value_info(n, s) for n, s in out_specs])
    imp = import_onnx(model)
    return imp.output(feeds)


def _conv_transpose_case(x, w, want, **attrs):
    nodes = [encode_node("ConvTranspose", ["x", "w"], ["y"], "ct",
                         **attrs)]
    got = _run(nodes, {"w": w}, [("x", x.shape)],
               [("y", tuple(want.shape))], {"x": x})[0]
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4,
                               atol=1e-4)


class TestConvTransposeModes:
    def test_grouped(self):
        x = R.randn(2, 4, 5, 5).astype(np.float32)
        w = R.randn(4, 3, 3, 3).astype(np.float32)  # C_in, C_out/g
        want = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                  stride=2, groups=2).numpy()
        _conv_transpose_case(x, w, want, strides=[2, 2], group=2)

    def test_dilated(self):
        x = R.randn(1, 3, 6, 6).astype(np.float32)
        w = R.randn(3, 2, 3, 3).astype(np.float32)
        want = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                  dilation=2).numpy()
        _conv_transpose_case(x, w, want, dilations=[2, 2])

    def test_output_padding(self):
        x = R.randn(1, 3, 5, 5).astype(np.float32)
        w = R.randn(3, 2, 3, 3).astype(np.float32)
        want = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                  stride=2, padding=1,
                                  output_padding=1).numpy()
        _conv_transpose_case(x, w, want, strides=[2, 2],
                             pads=[1, 1, 1, 1],
                             output_padding=[1, 1])

    def test_asymmetric_pads(self):
        x = R.randn(1, 2, 6, 6).astype(np.float32)
        w = R.randn(2, 2, 3, 3).astype(np.float32)
        # torch has no asymmetric transpose pads: emulate by slicing
        # the unpadded result ([pad_begin : size - pad_end])
        full = F.conv_transpose2d(torch.tensor(x),
                                  torch.tensor(w), stride=2).numpy()
        want = full[:, :, 1:full.shape[2] - 2, 0:full.shape[3] - 1]
        _conv_transpose_case(x, w, want, strides=[2, 2],
                             pads=[1, 0, 2, 1])

    def test_grouped_dilated_combo(self):
        x = R.randn(1, 4, 4, 4).astype(np.float32)
        w = R.randn(4, 2, 2, 2).astype(np.float32)
        want = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                  stride=2, dilation=2, padding=1,
                                  groups=2).numpy()
        _conv_transpose_case(x, w, want, strides=[2, 2],
                             dilations=[2, 2], pads=[1, 1, 1, 1],
                             group=2)

    def test_auto_pad_same_upper(self):
        x = R.randn(1, 2, 5, 5).astype(np.float32)
        w = R.randn(2, 3, 3, 3).astype(np.float32)
        # SAME_UPPER: output = input * stride
        s = 2
        full = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                  stride=s).numpy()
        total = 3 - s                       # ke - s = 1
        b = total // 2                      # extra at the END
        want = full[:, :, b:b + 5 * s, b:b + 5 * s]
        _conv_transpose_case(x, w, want, strides=[s, s],
                             auto_pad=b"SAME_UPPER")

    def test_auto_pad_same_upper_stride_exceeds_kernel(self):
        """stride > kernel extent: total padding goes NEGATIVE and
        must flow through (regression: a max(...,0) clamp shrank the
        output below input*stride)."""
        x = R.randn(1, 1, 5, 5).astype(np.float32)
        w = R.randn(1, 1, 1, 1).astype(np.float32)
        want = np.zeros((1, 1, 10, 10), np.float32)
        want[:, :, ::2, ::2] = x * w[0, 0, 0, 0]
        _conv_transpose_case(x, w, want, strides=[2, 2],
                             auto_pad=b"SAME_UPPER")

    def test_output_shape_attr(self):
        x = R.randn(1, 2, 4, 4).astype(np.float32)
        w = R.randn(2, 2, 3, 3).astype(np.float32)
        # output_shape=[9,9]: total_pad = 2*3+3-9 = 0 → full output
        want = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                  stride=2).numpy()
        _conv_transpose_case(x, w, want, strides=[2, 2],
                             output_shape=[9, 9])


class TestTopKModes:
    def test_smallest(self):
        x = R.randn(3, 8).astype(np.float32)
        nodes = [encode_node("TopK", ["x", "k"], ["v", "i"], "tk",
                             axis=-1, largest=0)]
        got = _run(nodes, {"k": np.asarray(3, np.int64)},
                   [("x", (3, 8))], [("v", (3, 3)), ("i", (3, 3))],
                   {"x": x})
        want_v, want_i = torch.topk(torch.tensor(x), 3, largest=False)
        np.testing.assert_allclose(np.asarray(got[0]), want_v.numpy(),
                                   atol=1e-6)
        np.testing.assert_array_equal(np.asarray(got[1]),
                                      want_i.numpy())

    def test_non_last_axis(self):
        x = R.randn(6, 4).astype(np.float32)
        nodes = [encode_node("TopK", ["x", "k"], ["v", "i"], "tk",
                             axis=0)]
        got = _run(nodes, {"k": np.asarray(2, np.int64)},
                   [("x", (6, 4))], [("v", (2, 4)), ("i", (2, 4))],
                   {"x": x})
        want_v, want_i = torch.topk(torch.tensor(x), 2, dim=0)
        np.testing.assert_allclose(np.asarray(got[0]), want_v.numpy(),
                                   atol=1e-6)
        np.testing.assert_array_equal(np.asarray(got[1]),
                                      want_i.numpy())

    def test_smallest_integer_dtype(self):
        """Smallest mode on int32 including 0 and INT32_MIN
        (regression: negation corrupted unsigned/INT_MIN orderings —
        INT_MIN negates to itself and ranked largest)."""
        x = np.asarray([[5, 0, np.iinfo(np.int32).min, 3]],
                       np.int32)
        nodes = [encode_node("TopK", ["x", "k"], ["v", "i"], "tk",
                             axis=-1, largest=0)]
        got = _run(nodes, {"k": np.asarray(2, np.int64)},
                   [("x", (1, 4))], [("v", (1, 2)), ("i", (1, 2))],
                   {"x": x})
        np.testing.assert_array_equal(
            np.asarray(got[0]),
            [[np.iinfo(np.int32).min, 0]])
        np.testing.assert_array_equal(np.asarray(got[1]), [[2, 1]])

    def test_smallest_non_last_axis(self):
        x = R.randn(5, 3, 4).astype(np.float32)
        nodes = [encode_node("TopK", ["x", "k"], ["v", "i"], "tk",
                             axis=1, largest=0)]
        got = _run(nodes, {"k": np.asarray(2, np.int64)},
                   [("x", (5, 3, 4))], [("v", (5, 2, 4))], {"x": x})
        want_v, _ = torch.topk(torch.tensor(x), 2, dim=1,
                               largest=False)
        np.testing.assert_allclose(np.asarray(got[0]), want_v.numpy(),
                                   atol=1e-6)


class TestCumSumModes:
    @pytest.mark.parametrize("exclusive,reverse", [(1, 0), (0, 1),
                                                   (1, 1)])
    def test_modes(self, exclusive, reverse):
        x = R.randn(4, 6).astype(np.float32)
        nodes = [encode_node("CumSum", ["x", "ax"], ["y"], "cs",
                             exclusive=exclusive, reverse=reverse)]
        got = _run(nodes, {"ax": np.asarray(1, np.int32)},
                   [("x", (4, 6))], [("y", (4, 6))], {"x": x})[0]
        ref = x[:, ::-1] if reverse else x
        want = np.cumsum(ref, axis=1)
        if exclusive:
            want = want - ref
        if reverse:
            want = want[:, ::-1]
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)

    def test_exclusive_with_inf(self):
        """Exclusive must SHIFT, not subtract: inf inputs produce NaN
        under inclusive-minus-self (regression)."""
        x = np.asarray([[1.0, np.inf, 2.0]], np.float32)
        nodes = [encode_node("CumSum", ["x", "ax"], ["y"], "cs",
                             exclusive=1)]
        got = _run(nodes, {"ax": np.asarray(1, np.int32)},
                   [("x", (1, 3))], [("y", (1, 3))], {"x": x})[0]
        np.testing.assert_array_equal(np.asarray(got),
                                      [[0.0, 1.0, np.inf]])


class TestLayerNormAxes:
    def test_non_last_axis_matches_torch(self):
        x = R.randn(3, 4, 5).astype(np.float32)
        scale = R.randn(4, 5).astype(np.float32)
        bias = R.randn(4, 5).astype(np.float32)
        nodes = [encode_node("LayerNormalization",
                             ["x", "scale", "bias"], ["y"], "ln",
                             axis=1)]
        got = _run(nodes, {"scale": scale, "bias": bias},
                   [("x", (3, 4, 5))], [("y", (3, 4, 5))],
                   {"x": x})[0]
        want = F.layer_norm(torch.tensor(x), (4, 5),
                            torch.tensor(scale),
                            torch.tensor(bias)).numpy()
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4,
                                   atol=1e-5)


class TestResize:
    """Resize/Upsample (torch F.interpolate export target — r4 verdict
    Missing #4's importer half; there was NO Resize mapping at all)."""

    def _resize_case(self, x, want, scales=None, sizes=None, **attrs):
        inputs = ["x", ""]               # roi always empty
        inits = {}
        if scales is not None:
            inputs = ["x", "", "scales"]
            inits["scales"] = np.asarray(scales, np.float32)
        if sizes is not None:
            inputs = ["x", "", "", "sizes"]
            inits["sizes"] = np.asarray(sizes, np.int64)
        nodes = [encode_node("Resize", inputs, ["y"], "rs", **attrs)]
        got = _run(nodes, inits, [("x", x.shape)],
                   [("y", tuple(want.shape))], {"x": x})[0]
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4,
                                   atol=2e-4)

    def test_nearest_upsample_matches_torch(self):
        x = R.randn(2, 3, 4, 5).astype(np.float32)
        want = F.interpolate(torch.tensor(x), scale_factor=2,
                             mode="nearest").numpy()
        self._resize_case(
            x, want, scales=[1, 1, 2, 2], mode=b"nearest",
            coordinate_transformation_mode=b"asymmetric",
            nearest_mode=b"floor")

    def test_nearest_fractional_matches_torch(self):
        x = R.randn(1, 2, 5, 7).astype(np.float32)
        want = F.interpolate(torch.tensor(x), size=(8, 11),
                             mode="nearest").numpy()
        self._resize_case(
            x, want, sizes=[1, 2, 8, 11], mode=b"nearest",
            coordinate_transformation_mode=b"asymmetric",
            nearest_mode=b"floor")

    def test_bilinear_matches_torch(self):
        x = R.randn(2, 3, 5, 6).astype(np.float32)
        want = F.interpolate(torch.tensor(x), size=(9, 11),
                             mode="bilinear",
                             align_corners=False).numpy()
        self._resize_case(
            x, want, sizes=[2, 3, 9, 11], mode=b"linear",
            coordinate_transformation_mode=b"half_pixel")

    def test_bicubic_matches_torch(self):
        x = R.randn(1, 2, 6, 7).astype(np.float32)
        want = F.interpolate(torch.tensor(x), size=(11, 13),
                             mode="bicubic",
                             align_corners=False).numpy()
        self._resize_case(
            x, want, sizes=[1, 2, 11, 13], mode=b"cubic",
            coordinate_transformation_mode=b"half_pixel",
            cubic_coeff_a=-0.75)

    def test_bicubic_downscale_matches_torch(self):
        x = R.randn(1, 2, 9, 8).astype(np.float32)
        want = F.interpolate(torch.tensor(x), size=(5, 6),
                             mode="bicubic",
                             align_corners=False).numpy()
        self._resize_case(
            x, want, sizes=[1, 2, 5, 6], mode=b"cubic",
            coordinate_transformation_mode=b"half_pixel",
            cubic_coeff_a=-0.75)

    def test_align_corners_rejected(self):
        x = R.randn(1, 1, 4, 4).astype(np.float32)
        nodes = [encode_node(
            "Resize", ["x", "", "scales"], ["y"], "rs", mode=b"linear",
            coordinate_transformation_mode=b"align_corners")]
        model = encode_model(
            nodes, {"scales": np.asarray([1, 1, 2, 2], np.float32)},
            [encode_value_info("x", x.shape)],
            [encode_value_info("y", (1, 1, 8, 8))])
        with pytest.raises(NotImplementedError):
            import_onnx(model).output({"x": x})

    def test_legacy_upsample_matches_torch(self):
        x = R.randn(1, 3, 4, 4).astype(np.float32)
        want = F.interpolate(torch.tensor(x), scale_factor=2,
                             mode="nearest").numpy()
        nodes = [encode_node("Upsample", ["x", "scales"], ["y"], "up",
                             mode=b"nearest")]
        got = _run(nodes,
                   {"scales": np.asarray([1, 1, 2, 2], np.float32)},
                   [("x", x.shape)], [("y", tuple(want.shape))],
                   {"x": x})[0]
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4,
                                   atol=2e-4)
