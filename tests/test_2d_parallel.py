"""First-class 2D parallelism — (data|fsdp × tensor) training modes on
the REAL fit path (ISSUE 12), on the virtual 8-device CPU mesh.

Covers: (dp,tp) / (sharded,tp) / (fsdp,tp) 4-step trajectory parity
with the dp-only dense baseline (Sgd / Nesterovs / Adam), physical
model-axis residency of the SpecLayout-inferred tp leaves, the
per-axis wire accounting invariant (dp update collectives move ZERO
bytes across the ``model`` axis), the graph and SameDiff step tails,
2D checkpoints restored onto a 1D mesh (and the remesh flavor), the
new telemetry surfaces, and the promotion of the MULTICHIP dp=2/tp=2
manual-collective dryrun into tier-1.

Trajectory tolerances follow test_fsdp.py: XLA reassociates the
update-tail reductions differently per layout, so parity is float32
noise, not bitwise.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.activations import Activation
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.learning.updaters import (TP_KEY, Adam,
                                                  Nesterovs, Sgd,
                                                  is_fsdp)
from deeplearning4j_tpu.lossfunctions import LossFunction
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.weights import WeightInit
from deeplearning4j_tpu.parallel import (ParallelWrapper, SpecLayout,
                                         UpdateExchange, make_mesh)
from deeplearning4j_tpu.parallel.zero import (exchange_report,
                                              update_exchange_axis_bytes)


def _mlp(updater=None, seed=42):
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed)
            .updater(updater or Sgd(0.1))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _graph(seed=7):
    conf = (NeuralNetConfiguration.Builder().seed(seed)
            .updater(Adam(0.01)).weight_init(WeightInit.XAVIER)
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(8))
            .add_layer("d1", DenseLayer(n_out=16,
                                        activation=Activation.TANH),
                       "in")
            .add_layer("out", OutputLayer(
                n_out=3, loss_function=LossFunction.MCXENT,
                activation=Activation.SOFTMAX), "d1")
            .set_outputs("out").build())
    return ComputationGraph(conf).init()


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 8).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, n)]
    return DataSet(x, y)


def _assert_tree_close(a, b, **kw):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), **kw)


def _dense(m):
    return m.dense_params() if hasattr(m, "dense_params") else m.params


def _build_2d(net, mode, workers=4, tp=2):
    return (ParallelWrapper.Builder(net).workers(workers)
            .tensor_parallel(tp).update_exchange(mode).build())


# -- trajectory parity ------------------------------------------------------
@pytest.mark.parametrize("mode", ["dense", "sharded", "fsdp"])
@pytest.mark.parametrize("updater,rtol,atol", [
    (lambda: Sgd(0.1), 1e-6, 1e-7),
    (lambda: Nesterovs(0.1, 0.9), 1e-5, 1e-6),
    (lambda: Adam(0.01), 1e-5, 1e-6),
], ids=["sgd", "nesterovs", "adam"])
def test_2d_trajectory_matches_dp_only_dense(mode, updater, rtol, atol):
    """The ISSUE acceptance bar: a (dp=4, tp=2) run in every exchange
    mode tracks the dp-only (8-way) dense baseline batch for batch —
    tp is a purely physical re-layout of the same math."""
    batches = [_data(64, seed=i) for i in range(4)]
    ref = _mlp(updater(), seed=7)
    pw_ref = ParallelWrapper.Builder(ref).workers(8) \
        .update_exchange("dense").build()
    net = _mlp(updater(), seed=7)
    pw = _build_2d(net, mode)
    for ds in batches:
        pw_ref.fit_batch(ds)
        pw.fit_batch(ds)
    assert pw.tensor_parallel == 2 and pw.n_workers == 4
    assert pw._tp_specs, "SpecLayout inferred no tp leaves"
    _assert_tree_close(ref.params, _dense(net), rtol=rtol, atol=atol)


def test_2d_tp_leaves_physically_model_sharded():
    """tp leaves keep FULL logical shapes but live physically sharded
    over the model axis; under fsdp they ride under TP_KEY outside the
    dp flats, resident at 1/(dp*tp)."""
    net = _mlp(seed=3)
    pw = _build_2d(net, "sharded")
    pw.fit_batch(_data(64, seed=0))
    specs = pw._tp_specs
    assert "layer_0" in specs and "W" in specs["layer_0"]
    W = net.params["layer_0"]["W"]
    assert W.shape == (8, 16)                  # logical shape intact
    shapes = {s.data.shape for s in W.addressable_shards}
    assert shapes == {(8, 8)}                  # 1/tp over model
    # fsdp×tp: same leaf moves OUT of the flats, under TP_KEY
    net_f = _mlp(seed=3)
    pw_f = _build_2d(net_f, "fsdp")
    pw_f.fit_batch(_data(64, seed=0))
    assert pw_f.update_exchange is UpdateExchange.FSDP
    ent = net_f.params["layer_0"]
    assert is_fsdp(ent) and TP_KEY in ent
    Wf = ent[TP_KEY]["W"]
    assert Wf.shape == (8, 16)
    assert {s.data.shape for s in Wf.addressable_shards} == {(2, 8)}


def test_axis_bytes_accounting_no_cross_axis_traffic():
    """update_exchange_axis_bytes: the dp update tail ravels over the
    ``data`` axis only — 0 bytes of dp collectives cross ``model``
    (the naive 1D ravel over all 8 devices WOULD cross it)."""
    net = _mlp()
    specs = SpecLayout(
        make_mesh({"data": 4, "model": 2}, jax.devices()[:8])
    ).infer(net.params, shard_over_data=True)
    rep = update_exchange_axis_bytes(net.params, 4, 2, specs)
    assert rep["model"] == 0
    assert rep["cross_axis_bytes"] == 0
    assert rep["naive_ravel_cross_axis_bytes"] > 0
    assert rep["tp_param_bytes"] > 0
    assert rep["data"] > 0
    # exchange_report folds the same block in per mode
    for mode in ("sharded", "fsdp"):
        r = exchange_report(net.params, 4, mode, model_shards=2,
                            tp_specs=specs)
        assert r["axis_bytes"]["model"] == 0
        assert r["axis_bytes"]["cross_axis_bytes"] == 0
    # a wrapper-built 2D run reports the same accounting
    pw = _build_2d(_mlp(), "sharded")
    pw.fit_batch(_data(64, seed=0))
    assert pw._axis_bytes["model"] == 0
    assert pw._axis_bytes["cross_axis_bytes"] == 0


# -- graph + SameDiff tails -------------------------------------------------
@pytest.mark.parametrize("mode", ["dense", "sharded", "fsdp"])
def test_graph_2d_matches_dp_only_dense(mode):
    batches = [_data(64, seed=i) for i in range(3)]
    ref = _graph(seed=7)
    pw_ref = ParallelWrapper.Builder(ref).workers(8) \
        .update_exchange("dense").build()
    g = _graph(seed=7)
    pw = _build_2d(g, mode)
    for ds in batches:
        pw_ref.fit_batch(ds)
        pw.fit_batch(ds)
    assert pw._tp_specs
    _assert_tree_close(ref.params, _dense(g), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("mode", ["dense", "sharded", "fsdp"])
def test_samediff_2d_matches_dp_only_dense(mode):
    from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig

    def build():
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 8))
        y = sd.placeholder("y", shape=(None, 3))
        rng = np.random.RandomState(7)
        sd.var("w1", array=(rng.randn(8, 16) * 0.3).astype(np.float32))
        sd.var("b1", array=np.zeros((16,), np.float32))
        sd.var("w2", array=(rng.randn(16, 3) * 0.3).astype(np.float32))
        sd.var("b2", array=np.zeros((3,), np.float32))
        h = sd.math.tanh(x @ sd.get_variable("w1")
                         + sd.get_variable("b1"))
        sd.loss.mean_squared_error(
            y, h @ sd.get_variable("w2") + sd.get_variable("b2"),
            name="loss")
        sd.set_loss_variables("loss")
        sd.set_training_config(
            TrainingConfig.Builder().updater(Adam(0.01))
            .data_set_feature_mapping("x")
            .data_set_label_mapping("y").build())
        return sd

    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(64, 8).astype(np.float32),
             "y": rng.randn(64, 3).astype(np.float32)}
    mesh1 = make_mesh({"data": 8}, jax.devices()[:8])
    mesh2 = make_mesh({"data": 4, "model": 2}, jax.devices()[:8])
    ref = build()
    l_ref = ref.fit_steps(batch, 4, mesh=mesh1, update_exchange="dense")
    sd = build()
    loss = sd.fit_steps(batch, 4, mesh=mesh2, update_exchange=mode)
    np.testing.assert_allclose(loss, l_ref, rtol=1e-5, atol=1e-7)
    for n in ("w1", "b1", "w2", "b2"):
        np.testing.assert_allclose(
            np.asarray(sd.get_variable(n).get_arr()),
            np.asarray(ref.get_variable(n).get_arr()),
            rtol=1e-5, atol=1e-6)
    # w1 [8,16] column-parallel: physically 1/tp (x 1/dp when ZeRO)
    shapes = {s.data.shape for s in sd._arrays["w1"].addressable_shards}
    assert shapes == ({(8, 8)} if mode == "dense" else {(2, 8)})
    # a second window resumes through the state-layout round trip
    l2 = sd.fit_steps(batch, 2, mesh=mesh2, update_exchange=mode)
    assert np.isfinite(float(l2))


# -- elasticity: 2D -> 1D ---------------------------------------------------
@pytest.mark.parametrize("mode", ["sharded", "fsdp"])
def test_2d_checkpoint_restores_onto_1d_mesh(tmp_path, mode):
    """A checkpoint written under (dp=4, tp=2) restores and CONTINUES
    on a plain dp-only 8-way mesh, tracking the uninterrupted dense
    trajectory (checkpoints densify, so they are layout-portable)."""
    from deeplearning4j_tpu.utils import CheckpointListener
    batches = [_data(64, seed=i) for i in range(4)]
    ref = _mlp(seed=11)
    pw_ref = ParallelWrapper.Builder(ref).workers(8) \
        .update_exchange("dense").build()
    for ds in batches:
        pw_ref.fit_batch(ds)

    net = _mlp(seed=11)
    lis = CheckpointListener(tmp_path, save_every_n_iterations=2)
    net.set_listeners(lis)
    pw = _build_2d(net, mode)
    for ds in batches[:2]:
        pw.fit_batch(ds)
    lis.flush()

    restored = CheckpointListener.load_checkpoint(tmp_path)
    assert restored.iteration_count == 2
    pw2 = ParallelWrapper.Builder(restored).workers(8) \
        .update_exchange(mode).build()
    assert pw2.tensor_parallel == 1
    for ds in batches[2:]:
        pw2.fit_batch(ds)
    _assert_tree_close(ref.params, _dense(restored),
                       rtol=2e-5, atol=1e-6)


@pytest.mark.parametrize("mode", ["sharded", "fsdp"])
def test_remesh_2d_to_1d_continues_trajectory(mode):
    """Live remesh flavor: train 2 batches at (4,2), hand remesh() an
    explicit 1D mesh (tp -> 1, pure DP), train 2 more — parameters
    keep tracking the fixed dense 8-way run."""
    batches = [_data(64, seed=i) for i in range(4)]
    ref = _mlp(seed=13)
    pw_ref = ParallelWrapper.Builder(ref).workers(8) \
        .update_exchange("dense").build()
    net = _mlp(seed=13)
    pw = _build_2d(net, mode)
    for i, ds in enumerate(batches):
        if i == 2:
            pw.remesh(mesh=make_mesh({"data": 8}, jax.devices()[:8]))
            assert pw.tensor_parallel == 1 and pw.n_workers == 8
        pw_ref.fit_batch(ds)
        pw.fit_batch(ds)
        _assert_tree_close(ref.params, _dense(net),
                           rtol=2e-5, atol=1e-6)
    # and the worker-count remesh PRESERVES tp (workers count dp
    # groups): shrink dp 4 -> 2 on the same tp=2 split
    pw2 = _build_2d(_mlp(seed=13), mode)
    pw2.fit_batch(batches[0])
    pw2.remesh(workers=2)
    assert pw2.tensor_parallel == 2 and pw2.n_workers == 2
    pw2.fit_batch(batches[1])


# -- telemetry surfaces -----------------------------------------------------
def test_2d_telemetry_surfaces():
    from deeplearning4j_tpu.common import telemetry
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    telemetry.MetricsRegistry._reset_for_tests()
    net = _mlp(Adam(0.01))
    pw = _build_2d(net, "sharded")
    pw.fit(ListDataSetIterator([_data(64)]), n_epochs=1)
    assert telemetry.gauge(
        "dl4j_tp_param_shard_bytes", "").value(
            model_shards=2, mode="sharded") > 0
    assert telemetry.counter(
        "dl4j_update_exchange_axis_bytes_total", "").value(
            axis="data") > 0
    # the 2D invariant, as a metric: zero dp-update bytes over model
    assert telemetry.counter(
        "dl4j_update_exchange_axis_bytes_total", "").value(
            axis="model") == 0


# -- builder validation -----------------------------------------------------
def test_tensor_parallel_builder_validation():
    with pytest.raises(ValueError, match="tensor_parallel"):
        ParallelWrapper.Builder(_mlp()).tensor_parallel(0)
    # 8 devices don't split into tp=3 groups
    with pytest.raises(ValueError):
        ParallelWrapper.Builder(_mlp()).tensor_parallel(3).build()
    # SharedTrainingMaster grows the same knob
    from deeplearning4j_tpu.parallel.sharedtraining import \
        SharedTrainingMaster
    tm = (SharedTrainingMaster.Builder(batch_size_per_worker=8)
          .update_exchange("sharded").tensor_parallel(2).build())
    assert tm.config.tensor_parallel == 2
    mesh = tm._global_mesh()
    assert mesh.shape["model"] == 2
    assert mesh.shape["data"] == len(jax.devices()) // 2


# -- MULTICHIP dp=2/tp=2 dryrun, promoted to tier-1 -------------------------
class TestDp2Tp2DryrunPromotion:
    """The manual-collective (shard_map) dryrun that MULTICHIP_r05 ran
    out-of-band, now asserted in-tree on a real 2D (data=2, model=2)
    submesh: batch sharded over ``data``, megatron column->row MLP
    over ``model``, forward AND backward equal to the dense math."""
    B, T, D, H, FF = 4, 8, 16, 2, 32

    def _mesh(self):
        return make_mesh({"data": 2, "model": 2}, jax.devices()[:4])

    def _x(self, seed=0):
        rng = np.random.RandomState(seed)
        return jnp.asarray(
            rng.randn(self.B, self.T, self.D).astype(np.float32))

    def _sharded(self, x):
        from deeplearning4j_tpu.parallel.mesh import shard_map
        from deeplearning4j_tpu.parallel.tensor import (
            init_tp_block_params, tp_mlp)
        mesh = self._mesh()

        def body(xs):
            rank = jax.lax.axis_index("model")
            p = init_tp_block_params(jax.random.PRNGKey(7), self.D,
                                     self.H, self.FF, tp=2,
                                     tp_rank=rank)
            return tp_mlp(xs, p["mlp"])

        spec = P("data", None, None)
        return shard_map(body, mesh, in_specs=(spec,),
                         out_specs=spec)(x)

    def _dense(self, x):
        from deeplearning4j_tpu.parallel.tensor import \
            init_tp_block_params
        p = init_tp_block_params(jax.random.PRNGKey(7), self.D, self.H,
                                 self.FF, tp=1, tp_rank=0)["mlp"]
        return jax.nn.gelu(x @ p["Wi"] + p["bi"]) @ p["Wo"] + p["bo"]

    def test_forward_matches_dense(self):
        x = self._x()
        np.testing.assert_allclose(np.asarray(self._sharded(x)),
                                   np.asarray(self._dense(x)),
                                   atol=1e-5)

    def test_backward_matches_dense(self):
        """shard_map autodiff transposes the collectives: d/dx of the
        dp×tp loss == d/dx of the dense loss (the model-axis psum's
        transpose + the data-axis batch split compose correctly)."""
        x = self._x(5)
        g1 = jax.grad(lambda z: jnp.sum(self._sharded(z) ** 2))(x)
        g2 = jax.grad(lambda z: jnp.sum(self._dense(z) ** 2))(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   atol=5e-4, rtol=1e-4)
