"""INDArray / Nd4j factory tests.

Modeled on the reference's backend-parameterized nd4j suites
(BaseNd4jTestWithBackends, SURVEY.md section 4.2) — here the single XLA
backend plays the role every backend had to pass.
"""
import numpy as np
import pytest

from deeplearning4j_tpu.common.dtypes import DataType
from deeplearning4j_tpu.ndarray import INDArray, Nd4j
from deeplearning4j_tpu.ops import transforms


class TestCreation:
    def test_zeros_ones(self):
        z = Nd4j.zeros(2, 3)
        assert z.shape == (2, 3)
        assert z.sum_number() == 0.0
        o = Nd4j.ones(4)
        assert o.sum_number() == 4.0

    def test_create_from_list(self):
        a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
        assert a.shape == (2, 2)
        assert a.get_double(1, 0) == 3.0

    def test_create_with_shape(self):
        a = Nd4j.create([1, 2, 3, 4, 5, 6], 2, 3)
        assert a.shape == (2, 3)
        assert a.get_double(1, 2) == 6.0

    def test_dtypes(self):
        a = Nd4j.zeros(2, 2, dtype=DataType.BFLOAT16)
        assert a.data_type() == DataType.BFLOAT16
        b = a.cast_to(DataType.FLOAT)
        assert b.data_type() == DataType.FLOAT

    def test_arange_linspace_eye(self):
        assert Nd4j.arange(5).length() == 5
        assert Nd4j.linspace(0, 1, 11).shape == (11,)
        assert Nd4j.eye(3).sum_number() == 3.0

    def test_rand_seeded_reproducible(self):
        Nd4j.get_random().set_seed(42)
        a = Nd4j.randn(3, 3)
        Nd4j.get_random().set_seed(42)
        b = Nd4j.randn(3, 3)
        assert a.equals(b)

    def test_one_hot(self):
        oh = Nd4j.one_hot([0, 2], 3)
        np.testing.assert_allclose(oh.to_numpy(),
                                   [[1, 0, 0], [0, 0, 1]])


class TestInPlaceAndViews:
    """The hard part: reference in-place/view aliasing semantics."""

    def test_addi_rebinds(self):
        a = Nd4j.ones(2, 2)
        b = a.addi(1.0)
        assert b is a
        assert a.sum_number() == 8.0

    def test_subi_on_view_writes_through_to_parent(self):
        a = Nd4j.zeros(3, 4)
        row = a.get_row(1)
        row.addi(5.0)
        assert a.sum_number() == 20.0
        assert a.get_double(1, 2) == 5.0
        assert a.get_double(0, 0) == 0.0

    def test_view_sees_parent_mutation(self):
        a = Nd4j.zeros(2, 2)
        v = a.get_column(0)
        a.addi(3.0)
        assert v.sum_number() == 6.0

    def test_nested_views(self):
        a = Nd4j.zeros(2, 3, 4)
        s = a.slice_view(1)          # shape (3,4)
        r = s.get_row(2)             # shape (4,)
        r.assign(7.0)
        assert a.get_double(1, 2, 3) == 7.0
        assert a.sum_number() == 28.0

    def test_setitem(self):
        a = Nd4j.zeros(3, 3)
        a[0, :] = Nd4j.ones(3)
        assert a.sum_number() == 3.0

    def test_put_scalar(self):
        a = Nd4j.zeros(2, 2)
        a.put_scalar((1, 1), 9.0)
        assert a.get_double(1, 1) == 9.0

    def test_assign_broadcasts(self):
        a = Nd4j.zeros(2, 3)
        a.assign(2.5)
        assert a.mean_number() == 2.5

    def test_dup_detaches(self):
        a = Nd4j.ones(2, 2)
        d = a.dup()
        d.addi(1.0)
        assert a.sum_number() == 4.0
        assert d.sum_number() == 8.0

    def test_tensor_along_dimension(self):
        a = Nd4j.arange(24).reshape(2, 3, 4).cast_to(DataType.FLOAT)
        assert a.tensors_along_dimension(2) == 6
        tad = a.tensor_along_dimension(1, 2)   # second row along last dim
        assert tad.shape == (4,)
        np.testing.assert_allclose(tad.to_numpy(), [4, 5, 6, 7])
        tad.addi(100.0)
        assert a.get_double(0, 1, 0) == 104.0


class TestMath:
    def test_elementwise(self):
        a = Nd4j.create([1.0, 2.0, 3.0])
        b = Nd4j.create([4.0, 5.0, 6.0])
        np.testing.assert_allclose((a + b).to_numpy(), [5, 7, 9])
        np.testing.assert_allclose((a * b).to_numpy(), [4, 10, 18])
        np.testing.assert_allclose((b / a).to_numpy(), [4, 2.5, 2])
        np.testing.assert_allclose(a.rsub(10.0).to_numpy(), [9, 8, 7])
        np.testing.assert_allclose(a.rdiv(6.0).to_numpy(), [6, 3, 2])

    def test_broadcasting(self):
        a = Nd4j.ones(2, 3)
        row = Nd4j.create([1.0, 2.0, 3.0])
        out = a.add(row)
        np.testing.assert_allclose(out.to_numpy(), [[2, 3, 4], [2, 3, 4]])

    def test_mmul(self):
        a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
        b = Nd4j.eye(2)
        assert a.mmul(b).equals(a)
        assert a.mmul(a).get_double(0, 0) == 7.0

    def test_gemm(self):
        a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
        out = Nd4j.gemm(a, a, transpose_b=True)
        np.testing.assert_allclose(out.to_numpy(), [[5, 11], [11, 25]])

    def test_reductions(self):
        a = Nd4j.create([[1.0, 2.0], [3.0, 4.0]])
        assert a.sum_number() == 10.0
        np.testing.assert_allclose(a.sum(0).to_numpy(), [4, 6])
        np.testing.assert_allclose(a.mean(1).to_numpy(), [1.5, 3.5])
        assert a.max_number() == 4.0
        assert float(a.norm1().to_numpy()) == 10.0
        np.testing.assert_allclose(float(a.norm2().to_numpy()),
                                   np.sqrt(30.0), rtol=1e-6)

    def test_argmax(self):
        a = Nd4j.create([[1.0, 5.0, 2.0], [7.0, 0.0, 3.0]])
        np.testing.assert_array_equal(a.argmax(1).to_numpy(), [1, 0])

    def test_std_bias_correction(self):
        a = Nd4j.create([1.0, 2.0, 3.0, 4.0])
        assert abs(float(a.std().to_numpy()) -
                   np.std([1, 2, 3, 4], ddof=1)) < 1e-6

    def test_comparisons(self):
        a = Nd4j.create([1.0, 2.0, 3.0])
        mask = a.gt(1.5)
        np.testing.assert_array_equal(mask.to_numpy(), [False, True, True])

    def test_shape_ops(self):
        a = Nd4j.arange(6).reshape(2, 3)
        assert a.transpose().shape == (3, 2)
        assert a.reshape(3, 2).shape == (3, 2)
        assert a.ravel().shape == (6,)
        b = Nd4j.arange(24).reshape(2, 3, 4)
        assert b.permute(2, 0, 1).shape == (4, 2, 3)

    def test_concat_stack(self):
        a, b = Nd4j.ones(2, 3), Nd4j.zeros(2, 3)
        assert Nd4j.concat(0, a, b).shape == (4, 3)
        assert Nd4j.concat(1, a, b).shape == (2, 6)
        assert Nd4j.stack(0, a, b).shape == (2, 2, 3)
        assert Nd4j.vstack(a, b).shape == (4, 3)

    def test_to_flattened(self):
        a, b = Nd4j.ones(2, 2), Nd4j.zeros(3)
        f = Nd4j.to_flattened(a, b)
        assert f.shape == (7,)
        assert f.sum_number() == 4.0


class TestTransforms:
    def test_basic(self):
        a = Nd4j.create([-1.0, 0.0, 1.0])
        np.testing.assert_allclose(transforms.relu(a).to_numpy(), [0, 0, 1])
        np.testing.assert_allclose(transforms.abs(a).to_numpy(), [1, 0, 1])
        s = transforms.sigmoid(Nd4j.zeros(1))
        assert abs(s.get_double(0) - 0.5) < 1e-6

    def test_softmax_sums_to_one(self):
        a = Nd4j.randn(4, 10)
        s = transforms.softmax(a)
        np.testing.assert_allclose(s.sum(1).to_numpy(), np.ones(4),
                                   rtol=1e-5)

    def test_distances(self):
        a = Nd4j.create([1.0, 0.0])
        b = Nd4j.create([0.0, 1.0])
        assert abs(transforms.cosine_sim(a, b)) < 1e-6
        np.testing.assert_allclose(transforms.euclidean_distance(a, b),
                                   np.sqrt(2), rtol=1e-6)
        assert transforms.manhattan_distance(a, b) == 2.0

    def test_unit_vec(self):
        v = transforms.unit_vec(Nd4j.create([3.0, 4.0]))
        np.testing.assert_allclose(v.to_numpy(), [0.6, 0.8], rtol=1e-6)


class TestProfiler:
    def test_nan_panic(self):
        from deeplearning4j_tpu.ops.executioner import (
            ND4JOpProfilerException, OpProfiler)
        prof = OpProfiler.get_instance()
        prof.config.check_for_nan = True
        try:
            a = Nd4j.create([1.0, float("nan")])
            with pytest.raises(ND4JOpProfilerException):
                a.add(1.0)
        finally:
            prof.config.check_for_nan = False

    def test_profiling_counts(self):
        from deeplearning4j_tpu.common.environment import Environment
        from deeplearning4j_tpu.ops.executioner import OpProfiler
        env = Environment.get()
        prof = OpProfiler.get_instance()
        prof.reset()
        env.profiling = True
        try:
            a = Nd4j.ones(2, 2)
            a.add(1.0)
            a.mmul(a)
            assert prof.stats["add"].invocations == 1
            assert prof.stats["mmul"].invocations == 1
        finally:
            env.profiling = False
