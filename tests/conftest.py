"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (SURVEY.md section 4.7 TPU
translation: multi-worker semantics in one process) so the full suite —
including sharding/collective tests — runs without TPU hardware. Real-TPU
runs can be forced with DL4J_TPU_TEST_PLATFORM=axon.

Note: this container's sitecustomize imports jax at interpreter start with
the axon (TPU tunnel) platform pinned; backend *initialization* is lazy, so
flipping jax_platforms + XLA_FLAGS here (before any jax.devices() call)
still works. Do not call jax.devices() at import time in any test module.
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

if os.environ.get("DL4J_TPU_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")
