"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (SURVEY.md section 4.7 TPU
translation: multi-worker semantics in one process) so the full suite —
including sharding/collective tests — runs without TPU hardware. Real-TPU
runs can be forced with DL4J_TPU_TEST_PLATFORM=axon.

Note: this container's sitecustomize imports jax at interpreter start with
the axon (TPU tunnel) platform pinned; backend *initialization* is lazy, so
flipping jax_platforms + XLA_FLAGS here (before any jax.devices() call)
still works. Do not call jax.devices() at import time in any test module.
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

if os.environ.get("DL4J_TPU_TEST_PLATFORM", "cpu") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

#: modules whose every test builds a multi-device mesh — on hardware
#: with fewer devices (e.g. the single-chip axon rig) they SKIP, not
#: fail: multi-device semantics are validated on the virtual CPU mesh
#: (SURVEY.md section 4.7), the same way the reference validates
#: Spark/parameter-server behavior in local/dummy-transport mode
_MESH_ONLY_MODULES = {
    "test_parallel", "test_tensor_parallel", "test_pipeline_parallel",
    "test_pipeline", "test_expert_parallel", "test_transformer_5d",
    "test_update_sharding", "test_fsdp", "test_elastic",
    "test_2d_parallel", "test_serving_sharded", "test_encoded",
}


def pytest_collection_modifyitems(config, items):
    have = len(jax.devices())
    if have >= 8:
        return
    skip = pytest.mark.skip(
        reason=f"multi-device suite needs the 8-device virtual mesh "
               f"(have {have} device(s); run without "
               f"DL4J_TPU_TEST_PLATFORM=axon)")
    for item in items:
        mod = item.module.__name__ if item.module else ""
        if mod in _MESH_ONLY_MODULES:
            item.add_marker(skip)


def require_devices(n: int):
    """Per-test guard for MIXED modules (some tests single-device,
    some mesh-based): skip when the platform has fewer devices."""
    have = len(jax.devices())
    if have < n:
        pytest.skip(f"needs {n} devices, have {have}")
