#!/usr/bin/env python
"""Trigger (or poll) an on-demand scaling-observatory profile capture
against a running training job's UIServer.

The CLI wrapper for ``POST /api/profile`` (the endpoint
``common.stepstats.ProfileCapture`` backs): starts a step-bounded
capture — ``jax.profiler`` device trace when available, plus the
observatory chrome trace and a merged timeline — then optionally polls
until the capture finalizes and prints where the artifacts landed.

Usage:

    python scripts/dl4j_profile.py --port 9000 --steps 50
    python scripts/dl4j_profile.py --url http://host:9000 --steps 20 \
        --wait
    python scripts/dl4j_profile.py --port 9000 --status

Exit 0 = capture started (or status fetched), 3 = a capture was
already active (HTTP 409), 1 = anything else.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.parse
import urllib.request


def _get(url: str) -> dict:
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read().decode())


def _post(url: str) -> tuple:
    req = urllib.request.Request(url, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default=None,
                    help="UIServer base URL (default: localhost:PORT)")
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--steps", type=int, default=20,
                    help="train steps to capture (bounded; the capture "
                    "auto-expires if the job stalls)")
    ap.add_argument("--out-dir", default=None,
                    help="server-side artifact directory (default: "
                    "under the flight-recorder dir)")
    ap.add_argument("--expire-seconds", type=float, default=None,
                    help="wall-clock auto-expiry override")
    ap.add_argument("--no-jax", action="store_true",
                    help="observatory trace only, skip jax.profiler")
    ap.add_argument("--wait", action="store_true",
                    help="poll until the capture finalizes")
    ap.add_argument("--status", action="store_true",
                    help="print capture status and exit")
    args = ap.parse_args(argv)

    base = args.url or f"http://127.0.0.1:{args.port}"
    base = base.rstrip("/")
    if args.status:
        print(json.dumps(_get(base + "/api/profile"), indent=2))
        return 0

    q = {"steps": str(args.steps)}
    if args.out_dir:
        q["out_dir"] = args.out_dir
    if args.expire_seconds is not None:
        q["expire_seconds"] = str(args.expire_seconds)
    if args.no_jax:
        q["jax"] = "0"
    code, body = _post(base + "/api/profile?"
                       + urllib.parse.urlencode(q))
    print(json.dumps(body, indent=2))
    if code == 409:
        print("capture already active (409)", file=sys.stderr)
        return 3
    if code != 200:
        return 1
    if not args.wait:
        return 0
    deadline = time.time() + (args.expire_seconds
                              or max(60.0, args.steps * 2.0)) + 30.0
    while time.time() < deadline:
        st = _get(base + "/api/profile")
        if not st.get("active"):
            print(json.dumps(st, indent=2))
            return 0
        time.sleep(1.0)
    print("timed out waiting for the capture to finalize",
          file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
