#!/usr/bin/env python
"""CI gate: the encoded-rung equivalence-and-compression contract.

Holds the ISSUE-20 acceptance bar on the virtual 8-device CPU mesh:

1. **Encoded trains** — ``update_exchange="encoded"`` resolves to the
   ENCODED rung on the real fit path and the 10-step loss trajectory
   actually descends (error-feedback residuals doing their job, not
   a silent dense fallback).
2. **Compression** — ``exchange_report`` at the observed sparsity
   shows ``encoded_wire_bytes`` strictly below the dense
   counterfactual for the same step.
3. **Telemetry live** — the ``dl4j_dp_encoding_sparsity`` gauge
   carries the live per-step transmitted fraction (0 < s <= 1), the
   ``dl4j_encoded_wire_bytes_total`` counter accumulated codec bytes,
   and ``dl4j_encoded_compression_ratio`` reads > 1.
4. **Zero cross-axis bytes** — encoded ×tp on a 2D ``(data, model)``
   mesh keeps the compressed dp exchange entirely off the model axis
   (the ``dl4j_update_exchange_axis_bytes_total`` model series
   stays 0).

Usage: JAX_PLATFORMS=cpu python scripts/check_encoded.py
Exit 0 = gate holds, 1 = a clause failed.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def _net(seed=0, n_in=16, hidden=32, n_out=4):
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.weights import WeightInit
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Adam(1e-2))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_in=n_in, n_out=hidden,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=n_out,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(n_in))
            .build())
    return MultiLayerNetwork(conf).init()


def _iterator(n=64, n_in=16, n_out=4, batch=32):
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    rng = np.random.RandomState(0)
    x = rng.randn(n, n_in).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.randint(0, n_out, n)]
    return ListDataSetIterator(DataSet(x, y), batch_size=batch)


def main() -> int:
    import jax

    from deeplearning4j_tpu.common import telemetry
    from deeplearning4j_tpu.common.telemetry import MetricsRegistry
    from deeplearning4j_tpu.parallel import ParallelWrapper
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.zero import (UpdateExchange,
                                                  exchange_report)

    if len(jax.devices()) < 8:
        print("FAIL: needs the virtual 8-device mesh "
              "(xla_force_host_platform_device_count=8)")
        return 1
    MetricsRegistry._reset_for_tests()
    failures = []

    # -- clauses 1-3: encoded trains, compresses, and reports ---------
    net = _net()
    it = _iterator()
    pw = ParallelWrapper.Builder(net).workers(8) \
        .update_exchange("encoded").build()
    loss0 = None
    for epoch in range(5):                     # 5 epochs x 2 batches
        pw.fit(it)
        if loss0 is None:
            loss0 = float(net.score(_iterator().next()))
    loss1 = float(net.score(_iterator().next()))
    if pw.update_exchange is not UpdateExchange.ENCODED:
        failures.append(f"clause 1: resolved {pw.update_exchange}, "
                        f"not ENCODED")
    if not loss1 < loss0:
        failures.append(f"clause 1: loss did not descend "
                        f"({loss0:.4f} -> {loss1:.4f})")
    print(f"clause 1: encoded rung trained, loss {loss0:.4f} -> "
          f"{loss1:.4f}")

    sp = pw._observed_encoding_sparsity()
    rep = exchange_report(net.params, 8, UpdateExchange.ENCODED,
                          encoding=pw.encoding, observed_sparsity=sp)
    if not rep["encoded_wire_bytes"] < rep["dense_wire_bytes"]:
        failures.append(
            f"clause 2: encoded wire {rep['encoded_wire_bytes']} not "
            f"< dense {rep['dense_wire_bytes']}")
    print(f"clause 2: encoded wire {rep['encoded_wire_bytes']} B < "
          f"dense {rep['dense_wire_bytes']} B "
          f"({rep['compression_ratio']:.1f}x)")

    scheme = pw.encoding.scheme
    g = telemetry.gauge("dl4j_dp_encoding_sparsity", "").value(
        scheme=scheme)
    wire = telemetry.counter(
        "dl4j_encoded_wire_bytes_total", "").value(scheme=scheme)
    ratio = telemetry.gauge(
        "dl4j_encoded_compression_ratio", "").value(scheme=scheme)
    if g is None or not (0.0 < float(g) <= 1.0):
        failures.append(f"clause 3: sparsity gauge not live ({g})")
    if not wire or wire <= 0:
        failures.append(f"clause 3: wire-bytes counter at {wire}")
    if ratio is None or float(ratio) <= 1.0:
        failures.append(f"clause 3: compression ratio gauge {ratio}")
    print(f"clause 3: sparsity gauge {g}, wire counter {wire} B, "
          f"ratio gauge {ratio}")

    # -- clause 4: encoded x tp keeps the model axis silent -----------
    MetricsRegistry._reset_for_tests()
    mesh2 = make_mesh({"data": 4, "model": 2}, jax.devices()[:8])
    net2 = _net(seed=7)
    pw2 = ParallelWrapper.Builder(net2).workers(8) \
        .update_exchange("encoded").mesh(mesh2).tensor_parallel(2) \
        .build()
    pw2.fit(_iterator())
    axis_c = telemetry.counter(
        "dl4j_update_exchange_axis_bytes_total", "")
    data_b = axis_c.value(axis="data") or 0
    model_b = axis_c.value(axis="model") or 0
    if pw2.update_exchange is not UpdateExchange.ENCODED:
        failures.append(f"clause 4: 2D resolved {pw2.update_exchange}")
    if not (data_b > 0 and model_b == 0):
        failures.append(f"clause 4: axis bytes data={data_b} "
                        f"model={model_b} (model axis must stay 0)")
    print(f"clause 4: axis bytes data={data_b} model={model_b}")

    if failures:
        for f in failures:
            print(f"FAIL {f}")
        return 1
    print("encoded gate: all clauses hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
