#!/usr/bin/env bash
# Repository CI gate — the fast, accelerator-free checks that keep the
# docs and the perf claims honest:
#
#   1. telemetry catalog sync: every registered dl4j_* metric is in
#      the README catalog (Observability / Diagnostics / Scaling
#      observatory sections) with the right type, and the catalog
#      documents nothing the code no longer registers
#      (scripts/check_telemetry_catalog.py);
#   2. bench regression gate: when at least two BENCH_r*.json rounds
#      are checked in, the newest must not regress any
#      known-polarity metric of the previous round by more than the
#      threshold — including the PR-9 `scaling` (efficiency up, skew
#      down) and `step_breakdown` (phase seconds down) blocks
#      (scripts/check_bench_regression.py);
#   3. fsdp residency gate: the ZeRO-3 bench leg on the virtual
#      8-device CPU mesh must measure per-chip param + updater-state
#      residency <= 1/4 of dense (the ISSUE 10 acceptance bar,
#      benchmarks/bench_fsdp.py);
#   4. chaos gate: a REAL SIGTERM mid-epoch in a subprocess must exit
#      75 after a final snapshot, and re-running the same command must
#      auto-resume onto the uninterrupted loss/parameter trajectory
#      with zero manual steps (the ISSUE 11 acceptance bar,
#      tests/test_chaos.py);
#   5. 2D equivalence gate: the (dp x tp) and (fsdp x tp) training
#      modes on the virtual 8-device mesh must track the dp-only dense
#      trajectory, keep the update exchange off the model axis, and
#      survive checkpoint/remesh back to 1D (the ISSUE 12 acceptance
#      bar, tests/test_2d_parallel.py);
#   6. kernel conformance gate: the Pallas conv/BN/ReLU epilogue
#      family must match the dense lowering bit-for-tolerance in
#      interpret mode (forward + gradients, incl. an f64
#      central-difference check) and every kernel family must
#      dispatch through the unified kernel-select ladder with
#      counted decisions (the ISSUE 13 acceptance bar,
#      tests/test_conv_pallas.py + tests/test_kernel_select.py);
#   7. layer-attribution conformance gate: per-layer flops/bytes on
#      LeNet + BERT-tiny must sum to the whole-model cost_analysis
#      within 1%, with the named-scope annotations actually reaching
#      the compiled HLO (the ISSUE 14 acceptance bar,
#      scripts/check_layer_attribution.py);
#   8. serving-SLO gate: a 2-replica router under concurrent load
#      across a live warm-then-drain rollout must answer every
#      request with a bitwise-correct 200 or a well-formed shed
#      (429/503 + integer Retry-After), drop nothing, and show zero
#      post-warmup retraces (the ISSUE 15 acceptance bar,
#      scripts/check_serving_slo.py);
#   9. generative conformance gate: paged-KV decode (Pallas kernel
#      forced, interpret mode) must be greedy-token-equal to the
#      dense full-re-forward reference, join/leave churn must never
#      retrace after warmup, and the KV pool must free every block
#      and reconcile with its dl4j_kv_pool_bytes gauge (the ISSUE 16
#      acceptance bar, scripts/check_generative.py);
#  10. request-tracing gate: one traced predict through the 2-replica
#      router must yield a connected span tree (every req.<phase>
#      span inside the request root, the root inside the router's
#      req.route envelope, durations consistent), echo the trace id
#      on the response with the latency-histogram exemplar carrying
#      it, and a forced shed storm must dump the request flight
#      recorder with per-phase timings (the ISSUE 17 acceptance bar,
#      scripts/check_request_tracing.py);
#  11. pipeline equivalence gate: pp=2 and pp2×dp on the virtual
#      8-device mesh must track the dp-only dense 4-step trajectory
#      (Sgd/Nesterovs/Adam, MLN + graph, both schedules), 1F1B must
#      hold strictly lower peak activation residency than GPipe at
#      equal n_micro, and pp checkpoints must restore onto a 1D mesh
#      (the ISSUE 18 acceptance bar, tests/test_pipeline.py);
#  12. static analysis gate: dl4j-lint (jit-purity, lock-discipline,
#      env-registry, metric-registry, spec-invariants) over the whole
#      tree must surface no finding outside the checked-in baseline,
#      and no rule's finding count may grow past its baselined count
#      (the ISSUE 19 acceptance bar, scripts/dl4j_lint);
#  13. encoded-rung equivalence-and-compression gate: the ENCODED
#      update exchange must train on the real fit path (loss
#      descends), exchange_report must show encoded_wire_bytes
#      strictly below the dense counterfactual, the live sparsity
#      gauge/wire counter/compression-ratio series must be populated,
#      and encoded ×tp on a 2D mesh must keep the compressed dp
#      exchange entirely off the model axis (the ISSUE 20 acceptance
#      bar, scripts/check_encoded.py).
#
# Usage: scripts/ci_check.sh [--threshold PCT]     (default 10)
# Exit 0 = all gates clean, 1 = a gate failed, 2 = bad usage.
set -u
cd "$(dirname "$0")/.."

THRESHOLD=10
while [ $# -gt 0 ]; do
  case "$1" in
    --threshold) THRESHOLD="$2"; shift 2 ;;
    *) echo "usage: $0 [--threshold PCT]" >&2; exit 2 ;;
  esac
done

fail=0

echo "== telemetry catalog sync =="
python scripts/check_telemetry_catalog.py || fail=1

echo "== bench regression gate =="
rounds=$(ls BENCH_r*.json 2>/dev/null | sort | tail -n 2)
n=$(printf '%s\n' "$rounds" | grep -c '[^[:space:]]')
if [ "$n" -lt 2 ]; then
  echo "fewer than two BENCH_r*.json rounds checked in; skipping"
else
  baseline=$(printf '%s\n' "$rounds" | head -n 1)
  fresh=$(printf '%s\n' "$rounds" | tail -n 1)
  echo "comparing $baseline -> $fresh (threshold ${THRESHOLD}%)"
  python scripts/check_bench_regression.py \
      --threshold "$THRESHOLD" "$baseline" "$fresh" || fail=1
fi

echo "== fsdp residency gate =="
fsdp_out=$(JAX_PLATFORMS=cpu python benchmarks/bench_fsdp.py) || fail=1
printf '%s\n' "$fsdp_out" | python -c '
import json, sys
lines = [l for l in sys.stdin if l.startswith("{")]
rec = json.loads(lines[-1]) if lines else {}
ok = rec.get("fsdp_resident_quarter_of_dense") is True
verdict = "OK" if ok else "FAIL: above 1/4 of dense"
ratio = rec.get("hbm_total_savings_ratio")
print(f"fsdp per-chip residency savings: {ratio}x ({verdict})")
sys.exit(0 if ok else 1)' || fail=1

echo "== chaos / auto-resume gate =="
JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q \
    -p no:cacheprovider || fail=1

echo "== 2D parallelism equivalence gate =="
JAX_PLATFORMS=cpu python -m pytest tests/test_2d_parallel.py -q \
    -p no:cacheprovider || fail=1

echo "== kernel conformance gate =="
JAX_PLATFORMS=cpu python -m pytest tests/test_conv_pallas.py \
    tests/test_kernel_select.py -q -p no:cacheprovider || fail=1

echo "== layer-attribution conformance gate =="
JAX_PLATFORMS=cpu python scripts/check_layer_attribution.py || fail=1

echo "== serving-SLO gate =="
JAX_PLATFORMS=cpu python scripts/check_serving_slo.py || fail=1

echo "== generative conformance gate =="
JAX_PLATFORMS=cpu python scripts/check_generative.py || fail=1

echo "== request-tracing gate =="
JAX_PLATFORMS=cpu python scripts/check_request_tracing.py || fail=1

echo "== pipeline equivalence gate =="
JAX_PLATFORMS=cpu python -m pytest tests/test_pipeline.py -q \
    -p no:cacheprovider || fail=1

echo "== static analysis gate =="
python -m scripts.dl4j_lint \
    --baseline scripts/dl4j_lint_baseline.json || fail=1

echo "== encoded-rung compression gate =="
JAX_PLATFORMS=cpu python scripts/check_encoded.py || fail=1

exit $fail
