#!/usr/bin/env python
"""Gate a fresh bench JSON against a baseline: exit non-zero on >N%
throughput (or step-time) regression.

The companion of ``bench.py``'s new ``meta`` block: once rounds are
comparable run-to-run, a regression becomes a checkable claim instead
of a diff someone eyeballs. Usage:

    python scripts/check_bench_regression.py BASELINE.json FRESH.json
    python scripts/check_bench_regression.py --threshold 5 r04.json r05.json

Accepted file shapes (auto-detected):

- a raw ``bench.py`` output line: ``{"metric": ..., "value": ...}``
- the BENCH_r*.json driver wrapper: ``{"n", "cmd", "rc", "tail",
  "parsed"}`` — ``parsed`` is used; if absent, the last JSON line in
  ``tail`` is.

Comparison: for every shared numeric metric with known polarity —
throughput-like (higher is better: ``value``, ``*_ips``, ``tflops``,
``throughput_rps``) and time-like (lower is better: ``*_ms``,
``*_us``, ``*_seconds``, ``*_pct`` overhead figures) — the fresh run
must not regress by more than ``--threshold`` percent. Improvements
never fail. Exit 0 = clean, 1 = regression(s), 2 = unusable input.

The PR-9 observatory blocks are understood natively: in ``scaling``,
per-size ``efficiency`` entries are higher-is-better and ``skew``
entries lower-is-better (matched on the full dotted path, since the
leaves are bare size/worker labels); ``step_breakdown`` phase means
gate as time-like seconds.  The ``fault_tolerance`` block's stall /
ratio / resume-latency figures gate as lower-is-better, as do any
``lost_steps`` counts.  The ISSUE-12 ``scaling_2d`` block gates
per-mode ``step_seconds`` / ``throughput_sps`` with the usual
polarities and its ``cross_axis`` / ``model_axis_update_bytes``
figures as lower-is-better (the 2D wire invariant: the update
exchange must not start crossing the model axis).  The ISSUE-13
``conv_kernels`` block gates with step time / compiled ``temp_bytes``
/ cost-analysis ``bytes_accessed`` lower-is-better and
``pct_of_roof`` / ``speedup`` / ``bytes_ratio`` higher-is-better —
the fused-epilogue claim is precisely "fewer HBM bytes, closer to
the roof".  The ISSUE-15 ``serving`` block gates its open-loop
percentiles (``p50/p95/p99_ms`` and the ``*_rtt_adj_ms`` twins)
lower-is-better and ``goodput_rps`` / ``in_slo_pct`` /
``occupancy_mean`` / the residency ``savings_ratio`` and
serialization ``speedup`` higher-is-better — the continuous-batching
claim is "lower tail latency AND more useful completions per second
at the same offered load"; ``meta.transport_rtt_ms`` rides in the
skipped ``meta`` block, so rig RTT never gates.  The ISSUE-17
``serving_observatory`` block gates its tracing-on/off p50 pair
(``p50_on_ms`` / ``p50_off_ms``) and ``trace_overhead_pct``
lower-is-better via the usual ``_ms`` / ``overhead`` rules — the
``_pct`` leaf compares in absolute points, holding the "tracing
default-on costs ≤1% on the predict hot path" claim round over
round.  The ISSUE-16
``generative`` block gates decode ``goodput_tokens_per_s`` and
``occupancy_mean`` higher-is-better; ``ttft_*_ms`` /
``intertoken_*_ms`` / the paged-vs-dense ``*_step_ms`` pair and any
``shed_rate`` lower-is-better — the paged-KV claim is "more tokens
per second at lower streaming tail latency, without shedding while
the pool sits half empty".  The ISSUE-18 ``pipeline`` block gates
its per-leg ``step_seconds`` / ``stage_idle_ms`` lower-is-better and
``throughput_rows_per_s`` higher-is-better via the usual rules, plus
``bubble_fraction`` and any scalar ``residency`` figure
lower-is-better — the 1F1B claim is "same bubble as GPipe, strictly
lower peak activation residency, no throughput give-back".  The
ISSUE-20 ``encoded`` block gates its ``wire_bytes`` /
``bytes_per_step`` (both arms and the ``dense_wire_bytes``
counterfactual) lower-is-better and ``compression_ratio``
higher-is-better — the compressed-collective claim is "strictly
fewer bytes on the data axis at the same step count, loss curve
within tolerance of uncompressed".

When baseline and fresh disagree on ``meta.proxy`` (one is a
CPU-proxy round, the other a real-chip round) the comparison is
skipped with a loud note and exit 0 — cross-rig numbers differ for
rig reasons, not code reasons.

Self-test (tier-1, no accelerator): comparing the checked-in
BENCH_r04.json to BENCH_r05.json must pass (r05 improved), and the
reverse direction at a tight threshold must flag the throughput drop
(see tests/test_diagnostics.py).
"""
from __future__ import annotations

import argparse
import json
import sys

#: metrics where larger is better (substring match on the key)
HIGHER_BETTER = ("value", "tflops", "throughput", "_ips", "_rps",
                 "efficiency", "savings_ratio", "pct_of_roof",
                 "speedup", "bytes_ratio", "goodput", "in_slo_pct",
                 "occupancy", "compression_ratio")
#: metrics where smaller is better
LOWER_BETTER = ("_ms", "_us", "_seconds", "overhead", "stall", "skew",
                "_bytes_per_chip", "lost_steps", "cross_axis",
                "model_axis_update_bytes", "temp_bytes",
                "bytes_accessed", "shed", "bubble_fraction",
                "residency", "wire_bytes", "bytes_per_step")
#: keys that are identity/config, never compared; "canary" keys are
#: clock-path checks documented as dispatch-noise-dominated
SKIP = ("metric", "unit", "n_trials", "vs_baseline", "meta", "min",
        "max", "telemetry", "memory", "canary")


def load_bench(path: str) -> dict:
    """The bench record from either a raw bench.py JSON line or a
    BENCH_r*.json driver wrapper."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object")
    if "metric" in doc:
        return doc
    parsed = doc.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        return parsed
    # wrapper without parsed: last JSON object line in the tail
    for line in reversed((doc.get("tail") or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "metric" in rec:
                return rec
    raise ValueError(f"{path}: no bench record found (neither a raw "
                     f"line, nor wrapper 'parsed'/'tail')")


def _flatten(rec: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in rec.items():
        if k in SKIP or any(s in k for s in ("canary",)):
            continue
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[key] = float(v)
    return out


def _polarity(key: str):
    # leaf first; nested blocks whose leaves are bare labels (the
    # `scaling` block's `efficiency.8`, `skew_seconds.3` — per-size /
    # per-worker maps) fall back to a full-path match
    for probe in (key.rsplit(".", 1)[-1], key):
        for pat in LOWER_BETTER:
            if pat in probe:
                return -1
        for pat in HIGHER_BETTER:
            if pat in probe:
                return +1
    return 0           # unknown polarity: informational only


def compare(baseline: dict, fresh: dict, threshold_pct: float):
    """(regressions, improvements, skipped) — each a list of
    (key, base, fresh, delta_pct) tuples; delta_pct is signed so that
    negative always means 'got worse'."""
    base_f, fresh_f = _flatten(baseline), _flatten(fresh)
    regressions, improvements, skipped = [], [], []
    for key in sorted(set(base_f) & set(fresh_f)):
        b, f = base_f[key], fresh_f[key]
        pol = _polarity(key)
        if pol == 0 or (b == 0 and not key.endswith("_pct")):
            skipped.append((key, b, f, 0.0))
            continue
        if key.rsplit(".", 1)[-1].endswith("_pct"):
            # already a percentage: compare in absolute points (a
            # noise-floor move like -0.9% -> 1.4% must not read as a
            # -256% relative regression)
            delta = pol * (f - b)
        else:
            delta = pol * (f - b) / abs(b) * 100     # + = improved
        row = (key, b, f, delta)
        if delta < -threshold_pct:
            regressions.append(row)
        elif delta > 0:
            improvements.append(row)
    return regressions, improvements, skipped


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("baseline", help="baseline bench JSON")
    ap.add_argument("fresh", help="fresh bench JSON to gate")
    ap.add_argument("--threshold", type=float, default=10.0,
                    help="max tolerated regression, percent "
                         "(default 10)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="only print regressions")
    args = ap.parse_args(argv)
    try:
        base = load_bench(args.baseline)
        fresh = load_bench(args.fresh)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    base_proxy = (base.get("meta") or {}).get("proxy")
    fresh_proxy = (fresh.get("meta") or {}).get("proxy")
    if base_proxy is not None and fresh_proxy is not None and \
            base_proxy != fresh_proxy:
        # one round ran on the chip, the other on the CPU proxy —
        # every number differs by orders of magnitude for rig
        # reasons, so a diff would be pure noise. Loud skip, clean
        # exit: this is "not comparable", not "regressed".
        print("SKIP: baseline and fresh disagree on meta.proxy "
              f"(baseline proxy={base_proxy}, fresh "
              f"proxy={fresh_proxy}) — a CPU-proxy round and a TPU "
              "round are not comparable; not gating.")
        return 0
    if base.get("metric") != fresh.get("metric"):
        print(f"error: metric mismatch — baseline "
              f"{base.get('metric')!r} vs fresh "
              f"{fresh.get('metric')!r}", file=sys.stderr)
        return 2
    regs, imps, _ = compare(base, fresh, args.threshold)
    for key, b, f, d in regs:
        print(f"REGRESSION {key}: {b:g} -> {f:g} ({d:+.1f}% vs "
              f"-{args.threshold:g}% allowed)")
    if not args.quiet:
        for key, b, f, d in imps:
            print(f"ok         {key}: {b:g} -> {f:g} ({d:+.1f}%)")
    if regs:
        print(f"{len(regs)} regression(s) beyond "
              f"{args.threshold:g}%", file=sys.stderr)
        return 1
    print(f"no regressions beyond {args.threshold:g}% "
          f"({len(imps)} improved)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
