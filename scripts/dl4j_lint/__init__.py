"""dl4j-lint: repo-native static analysis for the invariants the test
suite cannot see.

Four invariant classes in this codebase are enforced only by
convention, and each has been violated at least once (ISSUE 19):
trace-time impurity inside jitted code, lock discipline across the
threaded serving/telemetry modules, registry drift between code and
docs (env vars, metrics), and sharding invariants (the ``pipe`` axis,
donated buffers).  This package is a small AST-based analyzer — the
"IR" is the Python AST — with one rule per invariant class:

- ``jit-purity``       trace-time impurity reachable from jit roots
- ``lock-discipline``  unguarded shared-attribute mutation in
                       thread-starting classes
- ``env-registry``     DL4J_TPU_* reads vs environment.py + README
- ``metric-registry``  dl4j_* metric literals vs the README catalog
- ``spec-invariants``  no ``pipe`` in PartitionSpec; no use of donated
                       args after the jitted call

Run ``python -m scripts.dl4j_lint --baseline
scripts/dl4j_lint_baseline.json`` (ci_check.sh gate 12).  Findings are
gated on NEW debt only: a checked-in baseline grandfathers known
findings (each with a reason string), per-line suppressions
(``# dl4j-lint: disable=<rule>``) silence deliberate idioms at the
site, and the gate also fails when a rule's finding count grows past
its baselined count.
"""
from scripts.dl4j_lint.core import (  # noqa: F401
    FileContext, Finding, RepoContext, Rule, all_rules,
    build_repo_context, lint_repo, load_baseline, register,
)

# importing the rule modules registers them
from scripts.dl4j_lint import (  # noqa: F401
    rules_env, rules_jit, rules_lock, rules_metric, rules_spec,
)
