"""metric-registry: the README metrics catalog must match the code.

The single source of the code<->README metric-scanning logic — the
standalone ``scripts/check_telemetry_catalog.py`` (ci_check gate 1 and
a tier-1 test) is a thin wrapper over the module-level functions here,
and the dl4j-lint rule turns the same diffs into findings:

- a ``counter("dl4j_...")`` / ``gauge`` / ``histogram`` registration
  missing from the README catalog sections,
- a catalog entry no code registers (stale docs mislead as much as
  missing ones),
- a catalog Type column disagreeing with the registration kind (a
  counter documented as a gauge sends scrapers down the wrong
  rate()/delta() path).
"""
from __future__ import annotations

import re
from typing import Dict, Iterable, Set, Tuple

from scripts.dl4j_lint.core import (Finding, RepoContext, Rule,
                                    register)

#: metric registrations: counter("name" / gauge("name" /
#: histogram("name" — any receiver (telemetry module, a registry, or
#: the module-level helpers called bare inside telemetry.py)
_REG_RE = re.compile(
    r"\b(counter|gauge|histogram)\(\s*\n?\s*['\"](dl4j_[a-z0-9_]+)")

#: names prefixed dl4j_ anywhere in the README catalog section
_DOC_RE = re.compile(r"`(dl4j_[a-z0-9_]+)`")

#: catalog table rows: | `name` | kind | ...
_DOC_ROW_RE = re.compile(
    r"^\|\s*`(dl4j_[a-z0-9_]+)`\s*\|\s*(counter|gauge|histogram)\s*\|",
    re.M)

#: registrations that are deliberately NOT part of the public catalog
_EXEMPT = {"dl4j_bench_counter_total", "dl4j_bench_hist_seconds"}

#: README sections whose tables form the catalog
_CATALOG_SECTIONS = ("Observability", "Diagnostics",
                     "Scaling observatory", "Layer attribution",
                     "Fault tolerance & elasticity")


def registered_metrics(repo: RepoContext
                       ) -> Dict[str, Tuple[Set[str], str, int]]:
    """{name: ({kinds}, first-registration path, line)}."""
    out: Dict[str, Tuple[Set[str], str, int]] = {}
    for ctx in repo.files:
        # tests register throwaway dl4j_t_* fixtures — not catalog
        # material (same scan surface as the pre-lint checker)
        if ctx.rel.startswith("tests/"):
            continue
        for m in _REG_RE.finditer(ctx.text):
            kind, name = m.group(1), m.group(2)
            if name in _EXEMPT:
                continue
            line = ctx.text[:m.start()].count("\n") + 1
            if name in out:
                out[name][0].add(kind)
            else:
                out[name] = ({kind}, ctx.rel, line)
    return out


def documented_metrics(readme_text: str) -> Dict[str, str]:
    """{name: documented kind} from the catalog tables (names
    mentioned outside table rows count as documented with kind '')."""
    doc: Dict[str, str] = {}
    for heading in _CATALOG_SECTIONS:
        m = re.search(rf"## {re.escape(heading)}(.*?)(?:\n## |\Z)",
                      readme_text, re.S)
        if not m:
            continue
        section = m.group(1)
        for name in _DOC_RE.findall(section):
            doc.setdefault(name, "")
        doc.update({name: kind
                    for name, kind in _DOC_ROW_RE.findall(section)})
    return doc


@register
class MetricRegistryRule(Rule):
    name = "metric-registry"
    description = ("every registered dl4j_* metric must appear in the "
                   "README catalog with the right type, and the "
                   "catalog must document nothing stale")

    def check_repo(self, repo: RepoContext) -> Iterable[Finding]:
        reg = registered_metrics(repo)
        doc = documented_metrics(repo.readme())
        for name in sorted(set(reg) - set(doc)):
            kinds, rel, line = reg[name]
            yield Finding(
                rule=self.name, path=rel, line=line,
                message=(f"metric `{name}` ({'/'.join(sorted(kinds))})"
                         " is registered here but missing from the "
                         "README catalog"),
                key=f"{self.name}:missing:{name}")
        for name in sorted(set(doc) - set(reg)):
            yield Finding(
                rule=self.name, path="README.md", line=0,
                message=(f"README catalog documents `{name}` but no "
                         "code registers it (stale entry)"),
                key=f"{self.name}:stale:{name}")
        for name in sorted(reg):
            kinds, rel, line = reg[name]
            documented = doc.get(name)
            if documented and documented not in kinds:
                yield Finding(
                    rule=self.name, path=rel, line=line,
                    message=(f"metric `{name}` registered as "
                             f"{sorted(kinds)} but the README Type "
                             f"column says {documented!r}"),
                    key=f"{self.name}:kind:{name}")
