"""spec-invariants: sharding-spec and donation hygiene.

Two invariants from the pipeline work (PR 18):

- **no ``pipe`` in a PartitionSpec** — pipeline parallelism moves data
  between stages with explicit ``ppermute`` on stage-local arrays;
  putting the ``pipe`` axis in a GSPMD ``PartitionSpec`` re-introduces
  the all-stages-resident layout the stage-partitioned SpecLayout
  exists to avoid.  (The stage-STACKED flagship transformer shards its
  leading stage dimension over ``pipe`` by design — that file carries
  a file-level suppression explaining why.)
- **donated buffers are dead after the call** — an argument listed in
  ``donate_argnums`` is deallocated by the jitted call; referencing it
  afterwards in the same scope either crashes ("buffer donated") or,
  on backends that silently copy, un-donates the buffer and doubles
  peak memory.  The rule tracks ``f = jax.jit(g, donate_argnums=...)``
  bindings within a scope and flags loads of donated argument names
  after the call site, unless rebound first (``params = step(params)``
  is the idiom and stays clean).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from scripts.dl4j_lint.core import (FileContext, Finding, Rule,
                                    register)

_SPEC_NAMES = {"P", "PartitionSpec"}
_PIPE_AXES = {"pipe"}


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Literal donate_argnums of a jax.jit(...) call, else None."""
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else \
        getattr(fn, "id", "")
    if name not in ("jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return (v.value,)
        if isinstance(v, (ast.Tuple, ast.List)):
            out = []
            for el in v.elts:
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, int):
                    out.append(el.value)
                else:
                    return None
            return tuple(out)
        return None
    return None


@register
class SpecInvariantsRule(Rule):
    name = "spec-invariants"
    description = ("PartitionSpec literals must not use the pipe "
                   "axis; donated arguments must not be read after "
                   "the jitted call")

    def wants(self, rel: str) -> bool:
        return rel.startswith("deeplearning4j_tpu/")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        yield from self._check_pipe_specs(ctx)
        yield from self._check_donation(ctx)

    # -- pipe axis in PartitionSpec ------------------------------------
    def _check_pipe_specs(self, ctx: FileContext
                          ) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and (
                    (isinstance(node.func, ast.Name)
                     and node.func.id in _SPEC_NAMES)
                    or (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "PartitionSpec"))):
                continue
            axes: List[str] = []
            for arg in node.args:
                elts = arg.elts if isinstance(
                    arg, (ast.Tuple, ast.List)) else [arg]
                for el in elts:
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, str):
                        axes.append(el.value)
            bad = sorted(set(axes) & _PIPE_AXES)
            if bad:
                yield Finding(
                    rule=self.name, path=ctx.rel, line=node.lineno,
                    message=(f"PartitionSpec uses the `{bad[0]}` axis "
                             "— pipeline stages are stage-local "
                             "arrays moved by ppermute, never a GSPMD "
                             "sharding dimension"),
                    key=(f"{self.name}:{ctx.rel}:pipe-spec:"
                         f"L{node.lineno}"))

    # -- use-after-donation --------------------------------------------
    def _check_donation(self, ctx: FileContext) -> Iterable[Finding]:
        for scope in ast.walk(ctx.tree):
            if isinstance(scope, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Module)):
                yield from self._scan_scope(ctx, scope)

    def _scan_scope(self, ctx: FileContext, scope: ast.AST
                    ) -> Iterable[Finding]:
        #: var name -> donated positions, for jitted callables bound
        #: in THIS scope
        jitted: Dict[str, Tuple[int, ...]] = {}
        #: donated var -> (call line, callee) awaiting rebind
        dead: Dict[str, Tuple[int, str]] = {}

        def stmts(node: ast.AST) -> Iterable[ast.stmt]:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    continue
                if isinstance(child, ast.stmt):
                    yield child
                    yield from stmts(child)

        def own(stmt: ast.stmt) -> Iterable[ast.AST]:
            """The statement's own expressions — nested statements
            (and defs) are excluded; they arrive via ``stmts``."""
            stack: List[ast.AST] = [stmt]
            while stack:
                node = stack.pop()
                yield node
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.stmt, ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda, ast.ClassDef)):
                        continue
                    stack.append(child)

        def assigned_names(stmt: ast.stmt) -> Set[str]:
            out: Set[str] = set()
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = stmt.targets
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets = [stmt.target]
            elif isinstance(stmt, ast.For):
                targets = [stmt.target]
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
            return out

        for stmt in stmts(scope):
            # loads of dead names in this statement (excluding the
            # assignment targets handled below)
            for node in own(stmt):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in dead:
                    line, callee = dead[node.id]
                    yield Finding(
                        rule=self.name, path=ctx.rel,
                        line=node.lineno,
                        message=(f"`{node.id}` was donated to "
                                 f"`{callee}` (donate_argnums) on "
                                 f"line {line} and read again here — "
                                 "donated buffers are deallocated by "
                                 "the call"),
                        key=(f"{self.name}:{ctx.rel}:donated:"
                             f"{callee}:{node.id}"))
                    del dead[node.id]
            # track jit bindings: f = jax.jit(g, donate_argnums=...)
            if isinstance(stmt, ast.Assign) and \
                    isinstance(stmt.value, ast.Call):
                pos = _donated_positions(stmt.value)
                if pos is not None:
                    for t in stmt.targets:
                        base = t
                        while isinstance(base, ast.Attribute):
                            base = base.value
                        if isinstance(t, ast.Name):
                            jitted[t.id] = pos
            # calls of tracked jitted callables: mark donated args
            for node in own(stmt):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Name) and \
                        node.func.id in jitted:
                    for i in jitted[node.func.id]:
                        if i < len(node.args) and \
                                isinstance(node.args[i], ast.Name):
                            dead[node.args[i].id] = (node.lineno,
                                                     node.func.id)
            # rebinds resurrect the name (params = step(params, ...))
            for name in assigned_names(stmt):
                dead.pop(name, None)
