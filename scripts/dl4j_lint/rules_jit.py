"""jit-purity: no trace-time impurity reachable from jit roots.

``jax.jit`` runs the Python function ONCE at trace time and bakes the
result into the compiled program — a ``time.time()`` call, an env-var
read, a ``np.random`` draw, a telemetry increment, or a ``print``
inside a jitted function executes at trace time only and is frozen (or
silent) for every subsequent step.  PR 13 had to document exactly this
("jit freezes trace-time decisions"); this rule makes it structural.

Roots: functions decorated with / passed to ``jax.jit`` / ``pjit`` /
``jax.custom_vjp`` / ``jax.custom_jvp`` (including
``functools.partial(jax.jit, ...)`` decorators and ``f.defvjp(fwd,
bwd)`` registrations).  From each root the rule follows same-module
calls by name (bounded depth) and flags, anywhere reachable:

- ``time.*`` calls (``time``/``perf_counter``/``monotonic``/...)
- ``np.random.*`` / ``numpy.random.*`` (trace-frozen randomness — use
  ``jax.random`` with a threaded key)
- ``os.environ`` / ``os.getenv`` / ``Environment.get`` reads
- ``telemetry.*`` instrument calls (counters silently stop counting
  under jit — instrument the dispatch site instead)
- ``print`` calls
- ``global`` / ``nonlocal`` declarations (mutating enclosing state
  from traced code runs once, not per step)

Suppress deliberate trace-time gates at the site with
``# dl4j-lint: disable=jit-purity`` and a comment saying WHY the
frozen decision is intended.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from scripts.dl4j_lint.core import (FileContext, Finding, Rule,
                                    register)

_JIT_NAMES = {"jit", "pjit", "custom_vjp", "custom_jvp"}
_TIME_FNS = {"time", "perf_counter", "monotonic", "process_time",
             "thread_time", "sleep", "time_ns", "perf_counter_ns",
             "monotonic_ns"}
_MAX_DEPTH = 8


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_callable(node: ast.AST) -> bool:
    """Is this expression jax.jit / pjit / custom_vjp (possibly via
    functools.partial(jax.jit, ...))?"""
    d = _dotted(node)
    if d is not None:
        leaf = d.rsplit(".", 1)[-1]
        if leaf in _JIT_NAMES:
            return True
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        if d and d.rsplit(".", 1)[-1] == "partial" and node.args:
            return _is_jit_callable(node.args[0])
        # jax.jit(f, static_argnums=...) used as a decorator factory
        return _is_jit_callable(node.func)
    return False


class _Scope:
    """Lexical function-name resolution: module scope plus one nested
    namespace per function (jit bodies are usually local closures
    inside ``build_train_step``-style factories)."""

    def __init__(self, tree: ast.AST):
        self.defs: Dict[ast.AST, Dict[str, ast.AST]] = {}
        self.parent: Dict[ast.AST, Optional[ast.AST]] = {tree: None}
        self._index(tree, tree)

    def _index(self, node: ast.AST, owner: ast.AST) -> None:
        table = self.defs.setdefault(owner, {})
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                table[child.name] = child
                self.parent[child] = owner
                self._index(child, child)
            elif isinstance(child, (ast.ClassDef,)):
                # methods resolve within their class only; good enough
                self.parent[child] = owner
                self._index(child, child)
            else:
                self._index(child, owner)

    def resolve(self, owner: ast.AST, name: str) -> Optional[ast.AST]:
        node: Optional[ast.AST] = owner
        while node is not None:
            target = self.defs.get(node, {}).get(name)
            if target is not None:
                return target
            node = self.parent.get(node)
        return None


def _impurities(fn: ast.AST) -> Iterable[Tuple[int, str]]:
    """(line, what) for each impure construct directly in ``fn``'s
    body (nested defs excluded — they are reached via call edges)."""
    for node in _own_nodes(fn):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            kw = "global" if isinstance(node, ast.Global) else \
                "nonlocal"
            yield node.lineno, (f"`{kw} {', '.join(node.names)}` — "
                                "mutating enclosing state under jit "
                                "runs at trace time only")
            continue
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d is None:
            continue
        if d == "print":
            yield node.lineno, ("`print(...)` executes at trace time "
                                "only (use jax.debug.print)")
        elif d.startswith("time.") and \
                d.split(".", 1)[1] in _TIME_FNS:
            yield node.lineno, (f"`{d}(...)` is frozen at trace time "
                                "(time the dispatch site instead)")
        elif d.startswith(("np.random.", "numpy.random.")):
            yield node.lineno, (f"`{d}(...)` draws trace-frozen "
                                "randomness (thread a jax.random key)")
        elif d in ("os.getenv", "os.environ.get",
                   "Environment.get"):
            yield node.lineno, (f"`{d}(...)` reads the environment at "
                                "trace time — the decision is frozen "
                                "into the compiled program")
        elif d.startswith("telemetry."):
            yield node.lineno, (f"`{d}(...)` instruments trace time, "
                                "not execution — counters go silent "
                                "under jit")


def _own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk ``fn`` without descending into nested function defs."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _callees(fn: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in _own_nodes(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name):
            out.add(node.func.id)
    return out


@register
class JitPurityRule(Rule):
    name = "jit-purity"
    description = ("functions reachable from jax.jit/pjit/custom_vjp "
                   "roots must not read clocks, env, np.random, "
                   "telemetry, print, or mutate nonlocal state")

    def wants(self, rel: str) -> bool:
        return rel.startswith("deeplearning4j_tpu/")

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        scope = _Scope(ctx.tree)
        roots = self._roots(ctx.tree, scope)
        seen: Set[ast.AST] = set()
        for root_name, fn in roots:
            yield from self._scan(ctx, scope, fn, root_name, seen,
                                  depth=0)

    # -- root discovery ------------------------------------------------
    def _roots(self, tree: ast.AST, scope: _Scope
               ) -> List[Tuple[str, ast.AST]]:
        roots: List[Tuple[str, ast.AST]] = []

        def add(owner: ast.AST, expr: ast.AST) -> None:
            if isinstance(expr, ast.Lambda):
                roots.append(("<lambda>", expr))
            elif isinstance(expr, ast.Name):
                target = scope.resolve(owner, expr.id)
                if target is not None:
                    roots.append((expr.id, target))

        for owner, table in list(scope.defs.items()):
            for fn in table.values():
                for deco in getattr(fn, "decorator_list", ()):
                    if _is_jit_callable(deco):
                        roots.append((fn.name, fn))
        # call sites: jax.jit(f, ...), pjit(f), custom_vjp(f),
        # f.defvjp(fwd, bwd)
        for owner in scope.defs:
            for node in _own_nodes(owner):
                if not isinstance(node, ast.Call):
                    continue
                if _is_jit_callable(node.func) and node.args:
                    add(owner, node.args[0])
                d = _dotted(node.func)
                if d and d.endswith(".defvjp"):
                    for arg in node.args:
                        add(owner, arg)
        # dedupe by node identity, keep first name
        seen: Set[int] = set()
        out = []
        for name, fn in roots:
            if id(fn) not in seen:
                seen.add(id(fn))
                out.append((name, fn))
        return out

    # -- reachability scan ---------------------------------------------
    def _scan(self, ctx: FileContext, scope: _Scope, fn: ast.AST,
              root: str, seen: Set[ast.AST], depth: int
              ) -> Iterable[Finding]:
        if id(fn) in seen or depth > _MAX_DEPTH:
            return
        seen.add(id(fn))  # type: ignore[arg-type]
        fn_name = getattr(fn, "name", "<lambda>")
        via = root if fn_name == root else f"{root} -> {fn_name}"
        for line, what in _impurities(fn):
            token = what.split("`")[1].split("(")[0]
            yield Finding(
                rule=self.name, path=ctx.rel, line=line,
                message=f"jit root `{via}`: {what}",
                key=f"{self.name}:{ctx.rel}:{via}:{token}")
        for callee in sorted(_callees(fn)):
            target = scope.resolve(fn, callee)
            if target is not None:
                yield from self._scan(ctx, scope, target, root, seen,
                                      depth + 1)
