"""dl4j-lint core: file model, rule registry, suppressions, baseline.

The analyzer is deliberately boring: parse every Python file once
(:class:`FileContext` caches the AST), hand each file to every
registered rule's :meth:`Rule.check_file`, then hand the whole repo to
each rule's :meth:`Rule.check_repo` (the registry rules need the
global view — every metric registration vs one README).  A
:class:`Finding` carries a *stable key* (no line numbers — lines
drift) so the checked-in baseline survives unrelated edits.

Suppression layers, innermost first:

- ``# dl4j-lint: disable=<rule>[,<rule>...]`` on the flagged line or
  the line directly above silences that site (``all`` matches every
  rule) — for deliberate idioms, with the justification in the
  surrounding comment;
- ``# dl4j-lint: disable-file=<rule>[,...]`` anywhere in a file
  silences the rule for the whole file (``disable-file=all`` drops
  the file from repo-level scans too — what tests/test_lint.py uses
  so its seeded-violation fixtures never leak into the repo gate);
- the baseline JSON grandfathers known findings by key, each with a
  reason string; the gate fails only on NEW keys, or when a rule's
  total finding count grows past its baselined count.
"""
from __future__ import annotations

import ast
import json
import pathlib
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Set

#: top-level trees/files the runner walks (repo-relative)
SCAN_BASES = ("deeplearning4j_tpu", "benchmarks", "scripts",
              "examples", "tests")
SCAN_FILES = ("bench.py",)
#: never scanned: the analyzer itself (its sources talk ABOUT the
#: patterns it hunts)
EXCLUDE_DIRS = ("scripts/dl4j_lint",)

_SUPPRESS_RE = re.compile(
    r"#\s*dl4j-lint:\s*(disable|disable-file)=([a-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation.  ``key`` is the stable identity used for
    baseline matching — rule + path + a rule-chosen detail, never a
    line number."""
    rule: str
    path: str            # repo-relative, posix separators
    line: int            # 1-based; 0 = whole-file / repo-level
    message: str
    key: str

    def text(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.message}"

    def as_json(self) -> dict:
        return {"rule": self.rule, "path": self.path,
                "line": self.line, "message": self.message,
                "key": self.key}


class FileContext:
    """One parsed source file: text, lines, AST (None when the file
    does not parse — rules must tolerate that), and the suppression
    index."""

    def __init__(self, root: pathlib.Path, path: pathlib.Path):
        self.root = root
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text(encoding="utf-8", errors="replace")
        self.lines = self.text.splitlines()
        try:
            self.tree: Optional[ast.AST] = ast.parse(self.text)
        except SyntaxError:
            self.tree = None
        self._line_disable: Dict[int, Set[str]] = {}
        self.file_disable: Set[str] = set()
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip() for r in m.group(2).split(",")
                     if r.strip()}
            if m.group(1) == "disable-file":
                self.file_disable |= rules
            else:
                self._line_disable.setdefault(i, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        if self.file_disable & {rule, "all"}:
            return True
        for at in (line, line - 1):
            if self._line_disable.get(at, set()) & {rule, "all"}:
                return True
        return False


class RepoContext:
    """The whole scanned tree, parsed once and shared by every rule."""

    def __init__(self, root: pathlib.Path,
                 files: List[FileContext]):
        self.root = root
        self.files = files
        self._by_rel = {f.rel: f for f in files}

    def file(self, rel: str) -> Optional[FileContext]:
        return self._by_rel.get(rel)

    def readme(self) -> str:
        p = self.root / "README.md"
        return p.read_text() if p.exists() else ""


class Rule:
    """Base rule.  Subclasses set ``name``/``description`` and
    override :meth:`check_file` (per-file AST walks) and/or
    :meth:`check_repo` (global registry diffs, run once after every
    file is parsed)."""

    name = ""
    description = ""

    def wants(self, rel: str) -> bool:
        """Which files :meth:`check_file` runs on (repo-relative
        posix path)."""
        return True

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_repo(self, repo: RepoContext) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, Rule] = {}


def register(rule) -> Rule:
    """Class decorator (or instance call) adding a rule to the
    registry."""
    inst = rule() if isinstance(rule, type) else rule
    assert inst.name and inst.name not in _REGISTRY, inst.name
    _REGISTRY[inst.name] = inst
    return rule


def all_rules() -> Dict[str, Rule]:
    return dict(_REGISTRY)


def iter_source_files(root: pathlib.Path) -> List[pathlib.Path]:
    out: List[pathlib.Path] = []
    for base in SCAN_BASES:
        d = root / base
        if not d.is_dir():
            continue
        for p in sorted(d.rglob("*.py")):
            rel = p.relative_to(root).as_posix()
            if any(rel == e or rel.startswith(e + "/")
                   for e in EXCLUDE_DIRS):
                continue
            out.append(p)
    for name in SCAN_FILES:
        p = root / name
        if p.is_file():
            out.append(p)
    return out


def build_repo_context(root: pathlib.Path,
                       files: Optional[Iterable[pathlib.Path]] = None,
                       ) -> RepoContext:
    """Parse the scan tree (or an explicit file list) into a
    :class:`RepoContext`.  disable-file=all drops the file from EVERY
    scan, including the repo-level regex rules."""
    root = pathlib.Path(root).resolve()
    paths = list(files) if files is not None \
        else iter_source_files(root)
    ctxs = [FileContext(root, pathlib.Path(p).resolve())
            for p in paths]
    return RepoContext(root, [c for c in ctxs
                              if "all" not in c.file_disable])


def lint_repo(root: pathlib.Path,
              rule_names: Optional[Iterable[str]] = None,
              files: Optional[Iterable[pathlib.Path]] = None,
              ) -> List[Finding]:
    """Run the selected rules over the tree; returns unsuppressed
    findings sorted by (path, line, rule).  ``files`` overrides the
    default walk (what the CLI's positional paths and the fixture
    tests use)."""
    root = pathlib.Path(root).resolve()
    rules = [_REGISTRY[n] for n in (rule_names or sorted(_REGISTRY))]
    repo = build_repo_context(root, files)
    findings: List[Finding] = []
    for rule in rules:
        for ctx in repo.files:
            if rule.name in ctx.file_disable or not rule.wants(ctx.rel):
                continue
            for f in rule.check_file(ctx):
                if not ctx.suppressed(f.rule, f.line):
                    findings.append(f)
        for f in rule.check_repo(repo):
            ctx = repo.file(f.path)
            if ctx is not None and ctx.suppressed(f.rule, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    return findings


# ----------------------------------------------------------------------
# baseline: grandfathered debt, keyed stably, every entry justified
@dataclass
class Baseline:
    reasons: Dict[str, str] = field(default_factory=dict)

    @property
    def keys(self) -> Set[str]:
        return set(self.reasons)

    def rule_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for key in self.reasons:
            rule = key.split(":", 1)[0]
            counts[rule] = counts.get(rule, 0) + 1
        return counts


def load_baseline(path: pathlib.Path) -> Baseline:
    data = json.loads(pathlib.Path(path).read_text())
    reasons: Dict[str, str] = {}
    for entry in data.get("findings", ()):
        key, reason = entry["key"], entry.get("reason", "")
        if not reason:
            raise ValueError(
                f"baseline entry {key!r} has no reason string — "
                "every grandfathered finding must be justified")
        if key in reasons:
            raise ValueError(f"duplicate baseline key {key!r}")
        reasons[key] = reason
    return Baseline(reasons)


def write_baseline(path: pathlib.Path, findings: List[Finding],
                   old: Optional[Baseline] = None) -> None:
    """Regenerate the baseline from the current findings, keeping the
    reason strings of keys that persist; new keys get a TODO reason a
    human must replace before committing."""
    old_reasons = old.reasons if old else {}
    entries = [{"key": f.key,
                "reason": old_reasons.get(
                    f.key, "TODO: justify this entry or fix the "
                           "finding"),
                "message": f.message,
                "path": f.path}
               for f in findings]
    doc = {
        "_comment": ("dl4j-lint grandfathered findings. The CI gate "
                     "fails on any finding whose key is not here, and "
                     "when a rule's finding count grows past its "
                     "count here. Regenerate with: python -m "
                     "scripts.dl4j_lint --write-baseline "
                     "scripts/dl4j_lint_baseline.json "
                     "(then justify every TODO reason)."),
        "findings": entries,
    }
    pathlib.Path(path).write_text(json.dumps(doc, indent=2,
                                             sort_keys=False) + "\n")


@dataclass
class GateResult:
    new: List[Finding]
    grown: Dict[str, tuple]      # rule -> (current, baselined)
    stale: List[str]             # baseline keys that no longer fire
    findings: List[Finding]

    @property
    def failed(self) -> bool:
        return bool(self.new or self.grown)


def gate(findings: List[Finding], baseline: Baseline) -> GateResult:
    new = [f for f in findings if f.key not in baseline.keys]
    current_counts: Dict[str, int] = {}
    for f in findings:
        current_counts[f.rule] = current_counts.get(f.rule, 0) + 1
    base_counts = baseline.rule_counts()
    grown = {rule: (n, base_counts.get(rule, 0))
             for rule, n in current_counts.items()
             if n > base_counts.get(rule, 0)
             and not any(f.rule == rule for f in new)}
    fired = {f.key for f in findings}
    stale = sorted(baseline.keys - fired)
    return GateResult(new=new, grown=grown, stale=stale,
                      findings=findings)
