"""lock-discipline: shared attributes of thread-starting classes must
mutate under the class lock.

The threaded modules (serving, telemetry, the observatory sidecars)
follow one convention: a class that starts a ``threading.Thread``
targeting one of its own methods owns a ``self._lock``, and every
attribute the thread side shares with the public surface mutates under
it.  The PR-11 ``ParallelInference.shutdown`` race and the PR-17
``_drain_rate`` cold-window bug were both violations of exactly this,
found late, by review.  This rule finds them structurally.

Per class the rule computes:

- *thread entries*: methods (or closures inside methods) passed as
  ``target=`` to ``threading.Thread`` / ``threading.Timer`` created
  anywhere in the class;
- the intra-class call graph over ``self.method()`` edges, giving the
  set of *thread-reachable* methods;
- per-attribute mutation sites (``self.x = / += ...``, ``self.x[k]
  =``, and mutating method calls like ``self.x.append(...)``) and
  access sites.

An attribute is **shared** when it is (a) mutated in thread-reachable
code and touched anywhere else, or (b) mutated from two or more
distinct methods.  Every mutation site of a shared attribute outside
``__init__`` must sit lexically inside ``with self.<lock>:`` (any
attribute whose name contains ``lock``, ``cv`` or ``cond``).
Attributes only ever assigned boolean constants are exempt (CPython
guarantees a torn bool read cannot happen, and the codebase uses bare
bool flags as cheap latches); so are ``_reset_for_tests`` helpers.

The caller-holds-the-lock idiom (``_ensure_worker`` called under the
submit lock) is deliberate — suppress those sites with
``# dl4j-lint: disable=lock-discipline`` and say so in the comment.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from scripts.dl4j_lint.core import (FileContext, Finding, Rule,
                                    register)

#: run over the modules that actually start threads (ISSUE 19 list)
_SCOPE_PREFIXES = ("deeplearning4j_tpu/serving/",)
_SCOPE_FILES = {
    "deeplearning4j_tpu/parallel/inference.py",
    "deeplearning4j_tpu/common/telemetry.py",
    "deeplearning4j_tpu/common/stepstats.py",
    "deeplearning4j_tpu/common/faults.py",
    "deeplearning4j_tpu/common/tracectx.py",
    "deeplearning4j_tpu/common/httputil.py",
    "deeplearning4j_tpu/common/compilecache.py",
    "deeplearning4j_tpu/common/diagnostics.py",
    "deeplearning4j_tpu/ui/server.py",
}

_MUTATOR_METHODS = {"append", "appendleft", "extend", "insert", "add",
                    "remove", "discard", "pop", "popleft", "clear",
                    "update", "setdefault", "popitem"}

#: constructors whose instances synchronize internally — calling
#: .set()/.clear()/.put()/.get() on these is not a lock violation
_THREADSAFE_TYPES = {"Event", "Condition", "Semaphore",
                     "BoundedSemaphore", "Barrier", "Queue",
                     "SimpleQueue", "LifoQueue", "PriorityQueue",
                     "Lock", "RLock"}
_LOCKISH = ("lock", "cv", "cond")
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter"}
_EXEMPT_METHODS = {"__init__", "_reset_for_tests"}


def _is_lockish(expr: ast.AST) -> bool:
    """``self._lock`` / ``cls._instance_lock`` / ``Foo._cls_lock`` —
    any attribute whose terminal name smells like a lock."""
    if isinstance(expr, ast.Call):     # e.g. self._lock.acquire() no,
        return False                   # with takes the lock object
    if isinstance(expr, ast.Attribute):
        name = expr.attr.lower()
        return any(t in name for t in _LOCKISH)
    if isinstance(expr, ast.Name):
        name = expr.id.lower()
        return any(t in name for t in _LOCKISH)
    return False


class _MethodInfo:
    def __init__(self, node: ast.AST):
        self.node = node
        self.name = node.name
        self.calls: Set[str] = set()          # self.<m>() edges
        #: attr -> [(line, guarded, is_bool_const, via_mutator_call)]
        self.mutations: Dict[str, List[Tuple[int, bool, bool,
                                             bool]]] = {}
        #: attrs assigned plain containers ({} / [] / set() / deque())
        #: — the only ones where .append()/.update() count as
        #: mutations (on a domain object they are ordinary methods)
        self.containers: Set[str] = set()
        self.accesses: Set[str] = set()       # any self.<attr> touch
        #: attrs assigned from internally-synchronized constructors
        self.threadsafe: Set[str] = set()
        #: thread targets started here: method names / local closures
        self.thread_targets: List[object] = []


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and \
            node.value.id in ("self", "cls"):
        return node.attr
    return None


def _analyze_method(m: ast.AST) -> _MethodInfo:
    info = _MethodInfo(m)

    def walk(node: ast.AST, guarded: bool) -> None:
        if isinstance(node, ast.With):
            g = guarded or any(_is_lockish(item.context_expr)
                               for item in node.items)
            for item in node.items:
                walk(item.context_expr, guarded)
            for child in node.body:
                walk(child, g)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a closure runs later (often on the thread); analyze its
            # body unguarded unless the with-block wraps the *call*,
            # which we cannot see — treat as same guard state
            for child in ast.iter_child_nodes(node):
                walk(child, guarded)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            is_bool = isinstance(getattr(node, "value", None),
                                 ast.Constant) and \
                isinstance(node.value.value, bool)
            if isinstance(node, ast.AugAssign):
                is_bool = False
            val = getattr(node, "value", None)
            ctor = (val.func.attr if isinstance(val.func,
                                                ast.Attribute)
                    else getattr(val.func, "id", "")) \
                if isinstance(val, ast.Call) else ""
            safe_ctor = ctor in _THREADSAFE_TYPES
            container = ctor in _CONTAINER_CTORS or isinstance(
                val, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                      ast.DictComp, ast.SetComp))
            for t in targets:
                base = t
                sub = False
                while isinstance(base, ast.Subscript):
                    base = base.value
                    sub = True
                attr = _self_attr(base)
                if attr is not None:
                    info.mutations.setdefault(attr, []).append(
                        (node.lineno, guarded,
                         is_bool and not sub, False))
                    info.accesses.add(attr)
                    if safe_ctor and not sub:
                        info.threadsafe.add(attr)
                    if container and not sub:
                        info.containers.add(attr)
        if isinstance(node, ast.Call):
            # self.<attr>.append(...) style container mutation
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATOR_METHODS:
                attr = _self_attr(node.func.value)
                if attr is not None:
                    info.mutations.setdefault(attr, []).append(
                        (node.lineno, guarded, False, True))
            # self.<m>(...) intra-class call edge
            attr = _self_attr(node.func)
            if attr is not None:
                info.calls.add(attr)
            # threading.Thread(target=self.m) / threading.Timer(s, f)
            fname = node.func.attr if isinstance(node.func,
                                                 ast.Attribute) \
                else getattr(node.func, "id", "")
            if fname in ("Thread", "Timer"):
                cands = [kw.value for kw in node.keywords
                         if kw.arg == "target"]
                if fname == "Timer" and len(node.args) >= 2:
                    cands.append(node.args[1])
                for c in cands:
                    t = _self_attr(c)
                    if t is not None:
                        info.thread_targets.append(t)
                    elif isinstance(c, ast.Name):
                        info.thread_targets.append(("local", c.id))
        attr = _self_attr(node)
        if attr is not None:
            info.accesses.add(attr)
        for child in ast.iter_child_nodes(node):
            walk(child, guarded)

    for stmt in m.body:
        walk(stmt, guarded=False)
    return info


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = ("thread-starting classes must mutate shared "
                   "attributes under the class lock")

    def wants(self, rel: str) -> bool:
        return rel in _SCOPE_FILES or \
            any(rel.startswith(p) for p in _SCOPE_PREFIXES)

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef
                     ) -> Iterable[Finding]:
        methods: Dict[str, _MethodInfo] = {}
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                methods[node.name] = _analyze_method(node)
        # thread entries: targeted methods, plus any method that
        # starts a thread on a local closure (the closure's effects
        # were folded into that method's own info)
        entries: Set[str] = set()
        for name, info in methods.items():
            for t in info.thread_targets:
                if isinstance(t, str) and t in methods:
                    entries.add(t)
                elif isinstance(t, tuple):
                    entries.add(name)   # closure body lives in `name`
        if not entries:
            return
        # thread-reachable closure over self.<m>() edges
        reach: Set[str] = set()
        work = list(entries)
        while work:
            m = work.pop()
            if m in reach or m not in methods:
                continue
            reach.add(m)
            work.extend(methods[m].calls)
        # per-attribute aggregation
        mut_methods: Dict[str, Set[str]] = {}
        touching: Dict[str, Set[str]] = {}
        bool_only: Dict[str, bool] = {}
        threadsafe: Set[str] = set()
        containers: Set[str] = set()
        for info in methods.values():
            threadsafe |= info.threadsafe
            containers |= info.containers
        for name, info in methods.items():
            for attr, sites in info.mutations.items():
                real = [s for s in sites
                        if not s[3] or attr in containers]
                if not real:
                    continue
                if name not in _EXEMPT_METHODS:
                    mut_methods.setdefault(attr, set()).add(name)
                for _, _, is_bool, _ in real:
                    bool_only[attr] = bool_only.get(attr, True) \
                        and is_bool
            if name in _EXEMPT_METHODS:
                continue
            for attr in info.accesses:
                touching.setdefault(attr, set()).add(name)
        # shared = something actually crosses the thread boundary (or
        # two public methods race each other): mutated on the thread
        # side with readers outside it, mutated outside with thread
        # readers, or mutated from >= 2 methods not all on the thread
        # side.  Attributes that are bool-constant latches or
        # internally-synchronized objects are exempt.
        shared: Set[str] = set()
        for attr, in_methods in mut_methods.items():
            if bool_only.get(attr, False) or attr in threadsafe:
                continue
            mut_t = bool(in_methods & reach)
            mut_o = bool(in_methods - reach)
            acc_outside = bool(touching.get(attr, set()) - reach)
            if (mut_o or acc_outside) and \
                    (mut_t or len(in_methods) >= 2):
                shared.add(attr)
        for name, info in sorted(methods.items()):
            if name in _EXEMPT_METHODS:
                continue
            for attr in sorted(set(info.mutations) & shared):
                for line, guarded, _, via_call in \
                        info.mutations[attr]:
                    if guarded or (via_call
                                   and attr not in containers):
                        continue
                    side = "thread-reachable" if name in reach \
                        else "public-surface"
                    yield Finding(
                        rule=self.name, path=ctx.rel, line=line,
                        message=(
                            f"`{cls.name}.{name}` mutates shared "
                            f"attribute `self.{attr}` without holding "
                            f"the class lock ({side} site; the class "
                            f"starts threads targeting "
                            f"{sorted(entries)})"),
                        key=(f"{self.name}:{ctx.rel}:{cls.name}."
                             f"{name}:{attr}"))
