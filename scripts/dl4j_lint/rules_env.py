"""env-registry: every ``DL4J_TPU_*`` read must be documented twice.

``common/environment.py`` is the promised single place to learn the
knob surface, and the README env table is the operator-facing copy —
but ~50 reads live outside ``environment.py`` and nothing kept either
registry honest.  This rule diffs three sets:

- **reads**: every ``DL4J_TPU_<NAME>`` literal in the scanned tree
  (package, benchmarks, scripts, examples, tests, bench.py) outside
  the ``environment.py`` module docstring;
- **environment.py docs**: names in the ``common/environment.py``
  module docstring;
- **README docs**: names in ``README.md`` rows of the form
  ``| `DL4J_TPU_X` | ... |`` (the "## Environment variables" table).

Findings: a read missing from either registry, and a stale entry in
either registry that no code reads.  Fix by documenting (or deleting)
the variable — do not baseline doc drift.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, Tuple

from scripts.dl4j_lint.core import (Finding, RepoContext, Rule,
                                    register)

_VAR_RE = re.compile(r"DL4J_TPU_[A-Z0-9]+(?:_[A-Z0-9]+)*")
_ROW_RE = re.compile(r"^\|\s*`(DL4J_TPU_[A-Z0-9_]+)`\s*\|", re.M)

ENV_MODULE = "deeplearning4j_tpu/common/environment.py"


@register
class EnvRegistryRule(Rule):
    name = "env-registry"
    description = ("every DL4J_TPU_* read must be documented in "
                   "common/environment.py and the README env table")

    def check_repo(self, repo: RepoContext) -> Iterable[Finding]:
        reads: Dict[str, Tuple[str, int]] = {}   # first read site
        env_docs: set = set()
        for ctx in repo.files:
            text = ctx.text
            if ctx.rel == ENV_MODULE and ctx.tree is not None:
                # documentation = every docstring in the module (the
                # knob catalog lives in the Environment CLASS
                # docstring); reads = matches outside docstrings
                doc_spans = []
                for node in ast.walk(ctx.tree):
                    if isinstance(node, (ast.Module, ast.ClassDef,
                                         ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        doc = ast.get_docstring(node)
                        body = getattr(node, "body", [])
                        if doc and body:
                            first = body[0].value
                            doc_spans.append((first.lineno,
                                              first.end_lineno))
                            env_docs |= set(_VAR_RE.findall(doc))

                def in_doc(line: int) -> bool:
                    return any(a <= line <= b for a, b in doc_spans)

                matches = ((name, line) for name, line in
                           ((m.group(0),
                             text[:m.start()].count("\n") + 1)
                            for m in _VAR_RE.finditer(text))
                           if not in_doc(line))
            else:
                matches = ((m.group(0),
                            text[:m.start()].count("\n") + 1)
                           for m in _VAR_RE.finditer(text))
            for name, line in matches:
                reads.setdefault(name, (ctx.rel, line))
        readme = repo.readme()
        readme_docs = set(_ROW_RE.findall(readme))
        for name in sorted(set(reads) - env_docs):
            rel, line = reads[name]
            yield Finding(
                rule=self.name, path=rel, line=line,
                message=(f"`{name}` is read here but not documented "
                         f"in {ENV_MODULE}'s module docstring"),
                key=f"{self.name}:env-doc:{name}")
        for name in sorted(set(reads) - readme_docs):
            rel, line = reads[name]
            yield Finding(
                rule=self.name, path=rel, line=line,
                message=(f"`{name}` is read here but has no row in "
                         "the README '## Environment variables' "
                         "table"),
                key=f"{self.name}:readme:{name}")
        for name in sorted(readme_docs - set(reads)):
            yield Finding(
                rule=self.name, path="README.md", line=0,
                message=(f"README env table documents `{name}` but "
                         "no code reads it (stale row)"),
                key=f"{self.name}:stale-readme:{name}")
        for name in sorted(env_docs - set(reads)):
            yield Finding(
                rule=self.name, path=ENV_MODULE, line=0,
                message=(f"{ENV_MODULE} documents `{name}` but no "
                         "code reads it (stale docstring entry)"),
                key=f"{self.name}:stale-env-doc:{name}")
