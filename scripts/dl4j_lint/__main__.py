"""CLI: ``python -m scripts.dl4j_lint [options] [files...]``.

Exit codes: 0 clean (or every finding baselined), 1 findings the
baseline does not cover (or a rule's count grew past its baselined
count), 2 usage / bad baseline.
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time

from scripts.dl4j_lint.core import (all_rules, gate, lint_repo,
                                    load_baseline, write_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m scripts.dl4j_lint",
        description="repo-native static analysis (jit-purity, "
                    "lock-discipline, env/metric registries, "
                    "spec-invariants)")
    ap.add_argument("files", nargs="*",
                    help="lint only these files (default: the full "
                         "scan tree)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: this script's repo)")
    ap.add_argument("--baseline", default=None,
                    help="grandfathered-findings JSON; gates on NEW "
                         "findings only")
    ap.add_argument("--write-baseline", metavar="PATH", default=None,
                    help="write the current findings as the baseline "
                         "to PATH (keeps existing reasons) and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name, rule in sorted(all_rules().items()):
            print(f"{name:16s} {rule.description}")
        return 0

    root = pathlib.Path(args.root) if args.root else \
        pathlib.Path(__file__).resolve().parents[2]
    rule_names = None
    if args.rules:
        rule_names = [r.strip() for r in args.rules.split(",")
                      if r.strip()]
        unknown = set(rule_names) - set(all_rules())
        if unknown:
            print(f"unknown rules: {sorted(unknown)} "
                  f"(have: {sorted(all_rules())})", file=sys.stderr)
            return 2
    files = [pathlib.Path(f) for f in args.files] or None

    t0 = time.monotonic()
    findings = lint_repo(root, rule_names=rule_names, files=files)
    elapsed = time.monotonic() - t0

    if args.write_baseline:
        old = None
        p = pathlib.Path(args.write_baseline)
        if p.exists():
            try:
                old = load_baseline(p)
            except ValueError:
                old = None
        write_baseline(p, findings, old)
        print(f"wrote {len(findings)} baseline entries to {p} "
              f"(justify every TODO reason before committing)")
        return 0

    baseline = None
    if args.baseline:
        try:
            baseline = load_baseline(pathlib.Path(args.baseline))
        except (OSError, ValueError, KeyError,
                json.JSONDecodeError) as e:
            print(f"bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2

    if baseline is None:
        report, failed = findings, bool(findings)
        baselined = 0
    else:
        result = gate(findings, baseline)
        report, failed = result.new, result.failed
        baselined = len(findings) - len(result.new)

    if args.as_json:
        out = {
            "findings": [f.as_json() for f in report],
            "total": len(findings),
            "baselined": baselined,
            "seconds": round(elapsed, 3),
            "failed": failed,
        }
        if baseline is not None:
            out["stale_baseline_keys"] = result.stale
            out["grown_rules"] = {
                r: {"current": c, "baselined": b}
                for r, (c, b) in result.grown.items()}
        print(json.dumps(out, indent=2))
        return 1 if failed else 0

    for f in report:
        print(f.text())
    if baseline is not None:
        if result.grown:
            for rule, (cur, base) in sorted(result.grown.items()):
                print(f"FAIL: rule {rule} fired {cur}x but the "
                      f"baseline grandfathers only {base} — fix the "
                      "regression, do not grow the baseline")
        if result.stale:
            print(f"note: {len(result.stale)} baseline entries no "
                  "longer fire (debt paid down?) — regenerate with "
                  "--write-baseline to tighten the gate:")
            for key in result.stale:
                print(f"  - {key}")
    verdict = "FAIL" if failed else "OK"
    print(f"{verdict}: {len(report)} new finding(s), "
          f"{baselined} baselined, "
          f"{len(findings)} total, {elapsed:.1f}s")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
