#!/usr/bin/env python
"""Print a running training job's per-layer attribution table.

The CLI wrapper for ``GET /api/layers`` (the endpoint
``common.layerprof`` backs): fetches the last
``model.layer_report()`` — per-layer flops / bytes / roofline bound /
measured or estimated milliseconds, and the kernel-select decision
recorded for the layer's trace sites — and renders it as a table
sorted heaviest-first.

Usage:

    python scripts/dl4j_layers.py --port 9000
    python scripts/dl4j_layers.py --url http://host:9000 --json

Exit 0 = table printed, 3 = no report computed yet (HTTP 404),
1 = anything else.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request


def _get(url: str) -> tuple:
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}")


def _fmt_count(v) -> str:
    if v is None:
        return "-"
    for unit, div in (("G", 1e9), ("M", 1e6), ("K", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.0f}"


def _kernel_cell(ent: dict) -> str:
    kd = ent.get("kernel")
    if not kd:
        return "-"
    parts = []
    for name, d in kd.items():
        tag = "fused" if d.get("fused") else "dense"
        parts.append(f"{name}:{tag}({d.get('decision', '?')})")
    return ",".join(parts)


def render(report: dict) -> str:
    rows = [("layer", "type", "fwd_ms", "bwd_ms", "est_ms", "flops",
             "bytes", "bound", "pct_roof", "kernel")]
    for name, ent in report["layers"].items():
        rows.append((
            name, ent.get("type", "-"),
            f"{ent['fwd_ms']:.3f}" if "fwd_ms" in ent else "-",
            f"{ent['bwd_ms']:.3f}" if "bwd_ms" in ent else "-",
            f"{ent['est_ms']:.4f}",
            _fmt_count(ent["flops"]), _fmt_count(ent["bytes"]),
            ent["bound"],
            f"{ent['pct_of_roof']:.1f}" if ent.get("pct_of_roof")
            is not None else "-",
            _kernel_cell(ent),
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
             for r in rows]
    head = (f"model={report.get('model')} "
            f"time_source={report.get('time_source')} "
            f"coverage={report.get('coverage')}")
    return "\n".join([head] + lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", default=None,
                    help="UIServer base URL (default: localhost:PORT)")
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--json", action="store_true",
                    help="print the raw report JSON instead of a table")
    args = ap.parse_args(argv)

    base = (args.url or f"http://127.0.0.1:{args.port}").rstrip("/")
    try:
        code, body = _get(base + "/api/layers")
    except (urllib.error.URLError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if code == 404:
        print(body.get("error", "no layer report computed yet"),
              file=sys.stderr)
        return 3
    if code != 200:
        print(f"error: HTTP {code}: {body}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(body, indent=2))
    else:
        print(render(body))
    return 0


if __name__ == "__main__":
    sys.exit(main())
