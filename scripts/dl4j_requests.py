#!/usr/bin/env python
"""Render the request flight recorder as a slowest-N table.

The operator's view of "which requests were slow, and WHERE did their
time go": each row is one completed request from the recorder's ring —
trace id, model, kind, verdict, total latency, and the per-phase
millisecond breakdown (admit / queue / batch_wait / device / serialize
/ stream) its TraceContext collected.

Input is either a dump artifact or a live server:

  python scripts/dl4j_requests.py flightrec/reqrec_1234_shed_storm_1.jsonl
  python scripts/dl4j_requests.py --url http://127.0.0.1:8500 -n 20

``--url`` reads ``GET /api/reqrec`` off a running replica server or
router. Rows sort by total latency (slowest first); ``-n`` caps the
table (default 20). ``--json`` emits the selected records as JSONL
instead of the table (for piping into jq).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List

PHASES = ("admit", "queue", "batch_wait", "device", "serialize",
          "stream")


def load_dump(path: str) -> List[dict]:
    """Records from a ``reqrec_*.jsonl`` dump (meta line skipped)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("record") == "meta":
                continue
            out.append(rec)
    return out


def load_url(url: str, n: int) -> List[dict]:
    import urllib.request
    with urllib.request.urlopen(
            f"{url.rstrip('/')}/api/reqrec?n={n}", timeout=10) as r:
        return json.load(r)["requests"]


def render(records: List[dict], n: int) -> str:
    rows = sorted(records,
                  key=lambda r: -float(r.get("total_ms", 0.0)))[:n]
    if not rows:
        return "no request records"
    head = (f"{'trace':16s} {'model':12s} {'kind':8s} {'verdict':7s} "
            f"{'total':>8s} "
            + " ".join(f"{p:>10s}" for p in PHASES))
    lines = [head, "-" * len(head)]
    for r in rows:
        ph = r.get("phase_ms", {}) or {}
        cells = " ".join(
            f"{ph[p]:10.2f}" if p in ph else f"{'-':>10s}"
            for p in PHASES)
        lines.append(
            f"{str(r.get('trace_id', '?')):16s} "
            f"{str(r.get('model', '?'))[:12]:12s} "
            f"{str(r.get('kind', '?')):8s} "
            f"{str(r.get('verdict', '?')):7s} "
            f"{float(r.get('total_ms', 0.0)):8.2f} {cells}")
    lines.append(f"({len(rows)} of {len(records)} records; "
                 f"columns in ms)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="slowest-N serving requests with per-phase "
                    "latency breakdown")
    ap.add_argument("dump", nargs="?",
                    help="a reqrec_*.jsonl dump artifact")
    ap.add_argument("--url",
                    help="read the live ring off a server "
                         "(GET <url>/api/reqrec)")
    ap.add_argument("-n", type=int, default=20,
                    help="show the N slowest requests (default 20)")
    ap.add_argument("--json", action="store_true",
                    help="emit the selected records as JSONL")
    args = ap.parse_args(argv)
    if bool(args.dump) == bool(args.url):
        ap.error("pass exactly one of: a dump path, or --url")
    records = (load_dump(args.dump) if args.dump
               else load_url(args.url, max(args.n * 4, 100)))
    if args.json:
        rows = sorted(records,
                      key=lambda r: -float(r.get("total_ms", 0.0)))
        for r in rows[:args.n]:
            print(json.dumps(r))
    else:
        print(render(records, args.n))
    return 0


if __name__ == "__main__":
    sys.exit(main())
