"""Train and save the bundled pretrained zoo checkpoints
(SURVEY.md D15: the reference ZooModel ships usable pretrained
weights; zero egress forbids downloads, not shipping locally-trained
checkpoints).

Writes deeplearning4j_tpu/models/pretrained/{lenet,charrnn,
resnet_cifar}.zip plus meta.json recording the dataset (deterministic
synthetic surrogates — the only data in this container), the gate
each checkpoint passed, and the training config. Re-run this script
to regenerate; tests/test_pretrained_zoo.py enforces the gates on
the committed artifacts.
"""
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import numpy as np

OUT = (Path(__file__).resolve().parents[1] / "deeplearning4j_tpu" /
       "models" / "pretrained")

CHARRNN_TEXT = ("the quick brown fox jumps over the lazy dog. "
                "pack my box with five dozen liquor jugs. ") * 50


def train_lenet():
    from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
    from deeplearning4j_tpu.models.zoo import LeNet
    from deeplearning4j_tpu.utils import ModelSerializer

    net = LeNet(num_classes=10).init()
    train_it = MnistDataSetIterator(256, train=True, num_examples=20000)
    test_it = MnistDataSetIterator(512, train=False, num_examples=5000)
    for _ in range(3):
        net.fit(train_it)
    ev = net.evaluate(test_it)
    acc = float(ev.accuracy())
    if acc < 0.99:
        raise RuntimeError(f"LeNet gate failed: {acc:.4f} < 0.99")
    ModelSerializer.write_model(net, str(OUT / "lenet.zip"),
                                save_updater=False)
    return {"accuracy": round(acc, 4), "dataset": "synthetic-mnist",
            "epochs": 3, "train_examples": 20000}


def train_charrnn():
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import RnnOutputLayer
    from deeplearning4j_tpu.nn.conf.layers_recurrent import GravesLSTM
    from deeplearning4j_tpu.utils import ModelSerializer

    chars = sorted(set(CHARRNN_TEXT))
    idx = {c: i for i, c in enumerate(chars)}
    n = len(chars)
    seq_len = 32
    ids = np.asarray([idx[c] for c in CHARRNN_TEXT], np.int32)
    starts = np.arange(0, len(ids) - seq_len - 1, seq_len)
    eye = np.eye(n, dtype=np.float32)
    x = np.stack([eye[ids[s:s + seq_len]] for s in starts])
    y = np.stack([eye[ids[s + 1:s + seq_len + 1]] for s in starts])

    conf = (NeuralNetConfiguration.Builder()
            .seed(12345)
            .updater(Adam(5e-3))
            .list()
            .layer(GravesLSTM(n_out=128, activation=Activation.TANH))
            .layer(RnnOutputLayer(n_out=n,
                                  loss_function=LossFunction.MCXENT,
                                  activation=Activation.SOFTMAX))
            .set_input_type(InputType.recurrent(n, seq_len))
            .build())
    net = MultiLayerNetwork(conf).init()
    for _ in range(60):
        net.fit(x, y)
    probs = np.asarray(net.output(x))
    acc = float((probs.argmax(-1) == y.argmax(-1)).mean())
    if acc < 0.90:
        raise RuntimeError(f"char-RNN gate failed: {acc:.4f} < 0.90")
    ModelSerializer.write_model(net, str(OUT / "charrnn.zip"),
                                save_updater=False)
    return {"next_char_accuracy": round(acc, 4), "hidden": 128,
            "seq_len": seq_len, "chars": "".join(chars)}


def train_resnet_cifar():
    from deeplearning4j_tpu.datasets.vision import Cifar10DataSetIterator
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.models.zoo import ResNet50
    from deeplearning4j_tpu.utils import ModelSerializer

    net = ResNet50(num_classes=10, height=32, width=32,
                   updater=Adam(1e-3),
                   STAGES=((2, 16), (2, 32))).init()
    train_it = Cifar10DataSetIterator(256, train=True,
                                      num_examples=10000)
    test_it = Cifar10DataSetIterator(512, train=False,
                                     num_examples=2000)
    for _ in range(3):
        net.fit(train_it)
    ev = net.evaluate(test_it)
    acc = float(ev.accuracy())
    if acc < 0.90:
        raise RuntimeError(f"ResNet-CIFAR gate failed: {acc:.4f} < 0.90")
    from deeplearning4j_tpu.models.pretrained_gates import (
        HARD_GATE, HARD_TEMPLATE_WEIGHT, eval_resnet_cifar_hard)
    hard = eval_resnet_cifar_hard(net)
    if not HARD_GATE[0] <= hard < HARD_GATE[1]:
        raise RuntimeError(
            f"ResNet-CIFAR hard-split gate failed: {hard:.4f} "
            f"outside {HARD_GATE}")
    ModelSerializer.write_model(net, str(OUT / "resnet_cifar.zip"),
                                save_updater=False)
    return {"accuracy": round(acc, 4),
            "hard_split_accuracy": round(hard, 4),
            "hard_split_template_weight": HARD_TEMPLATE_WEIGHT,
            "dataset": "synthetic-cifar10",
            "stages": [[2, 16], [2, 32]], "epochs": 3,
            "train_examples": 10000}


def main():
    OUT.mkdir(parents=True, exist_ok=True)
    meta = {"lenet": train_lenet(),
            "charrnn": train_charrnn(),
            "resnet_cifar": train_resnet_cifar()}
    with open(OUT / "meta.json", "w") as fh:
        json.dump(meta, fh, indent=2)
    for name, m in meta.items():
        size = os.path.getsize(OUT / f"{name}.zip")
        print(f"{name}: {m} ({size / 1e6:.2f} MB)")


if __name__ == "__main__":
    main()
