#!/usr/bin/env python
"""CI gate: the generative-serving conformance contract.

Holds the ISSUE-16 acceptance bar on the CPU backend (the Pallas
paged-decode kernel runs in interpret mode — same arithmetic, no
accelerator needed):

1. **Paged == dense** — greedy decode through the full engine with
   the paged kernel FORCED (``DL4J_TPU_PAGED_ATTENTION=1``) must
   produce token-for-token the same ids as
   ``DecoderLM.reference_decode`` (a full dense re-forward per step,
   no KV cache at all) for a spread of prompts and lengths.
2. **Zero post-warmup retraces across churn** — staggered submits
   with different max_tokens make sequences join and leave the
   decode batch mid-flight; the engine's RetraceGuard must record
   ZERO new signatures after warmup (continuous batching never
   recompiles in steady state).
3. **Pool accounting reconciles** — every block allocated during the
   churn must be back on the free list afterwards, and
   ``diagnostics.memory_report()`` must carry the pool as its own
   resident class with bytes equal to the ``dl4j_kv_pool_bytes``
   gauge.

Usage: JAX_PLATFORMS=cpu python scripts/check_generative.py
Exit 0 = gate holds, 1 = a clause failed.
"""
from __future__ import annotations

import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
# clause 1 forces the paged kernel everywhere the ladder consults the
# env override — set before any engine import
os.environ["DL4J_TPU_PAGED_ATTENTION"] = "1"

import numpy as np  # noqa: E402


def main() -> int:
    from deeplearning4j_tpu.common import diagnostics
    from deeplearning4j_tpu.models.decoder import (DecoderConfig,
                                                   DecoderLM)
    from deeplearning4j_tpu.serving.generative import DecodeEngine
    from deeplearning4j_tpu.serving.kvcache import (KVBlockPool,
                                                    _bytes_gauge)

    failures = []
    conf = DecoderConfig.tiny()
    model = DecoderLM(conf)
    params = model.init()
    pool = KVBlockPool(conf.n_layers, 64, 8, conf.n_heads,
                       conf.head_dim, name="gate")
    eng = DecodeEngine(model, params, pool, name="gate",
                       prompt_buckets=(16,), decode_buckets=(4, 8),
                       max_seq_len=64, paged=True)
    eng.warmup()

    # -- clause 1: paged greedy == dense full-re-forward reference ----
    rng = np.random.default_rng(7)
    cases = [(rng.integers(2, 60, size=n), m)
             for n, m in ((3, 10), (8, 6), (13, 12), (1, 4))]
    for prompt, max_tokens in cases:
        got = list(eng.submit(prompt, max_tokens))
        ref = list(model.reference_decode(params, prompt, max_tokens,
                                          eos_id=conf.eos_id))
        if got != ref:
            failures.append(
                f"paged != dense for prompt len {prompt.size}: "
                f"{got} vs {ref}")
    print(f"clause 1: {len(cases)} paged-vs-reference greedy decodes "
          f"compared")

    # -- clause 2: join/leave churn, zero retraces --------------------
    streams, toks = [], {}
    lock = threading.Lock()

    def client(i):
        prompt = rng.integers(2, 60, size=int(rng.integers(2, 14)))
        s = eng.submit(prompt, int(rng.integers(2, 10)),
                       temperature=0.8 if i % 3 else 0.0,
                       top_k=20 if i % 2 else 0)
        got = list(s)
        with lock:
            toks[i] = (got, s.reason)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if len(toks) != 12 or any(not g for g, _ in toks.values()):
        failures.append(f"churn: {len(toks)}/12 sequences completed, "
                        f"some empty: {toks}")
    retraces = eng.retraces_since_warmup()
    if retraces != 0:
        failures.append(f"{retraces} post-warmup retraces across "
                        f"join/leave churn (must be 0)")
    print(f"clause 2: 12 churning sequences decoded, "
          f"{retraces} post-warmup retraces")

    # -- clause 3: pool accounting reconciles -------------------------
    if pool.live_blocks != 0 or pool.live_sequences != 0:
        failures.append(
            f"pool leak after churn: {pool.live_blocks} blocks / "
            f"{pool.live_sequences} sequences still live")
    report = diagnostics.memory_report()
    pools = report.get("kv_pools", [])
    if not pools:
        failures.append("memory_report carries no kv_pools resident "
                        "class")
    gauge_bytes = _bytes_gauge().value(pool="gate")
    if pools and int(gauge_bytes) != int(report["kv_pool_bytes"]):
        failures.append(
            f"kv_pool_bytes gauge ({gauge_bytes}) != memory_report "
            f"({report['kv_pool_bytes']})")
    if report["kv_pool_bytes"] <= 0:
        failures.append("kv pool accounts zero bytes")
    print(f"clause 3: pool fully freed, {report['kv_pool_bytes']} "
          f"bytes reconciled with the gauge")
    eng.shutdown()

    if failures:
        for f in failures:
            print(f"FAIL: {f}")
        return 1
    print("OK: paged decode is token-equal to the dense reference, "
          "churn never retraced, and the pool reconciles")
    return 0


if __name__ == "__main__":
    sys.exit(main())
