#!/usr/bin/env python
"""CI gate: one traced request yields a connected span tree.

Boots a 2-replica :class:`ServingRouter`, sends ONE predict request
through the router with an explicit ``X-Dl4j-Trace-Id``, and checks
the observatory's structural contract end to end:

1. the response echoes the trace id and stamps ``X-Dl4j-Replica``;
2. the chrome-trace ring holds exactly one ``request`` root span for
   the trace id, and every ``req.<phase>`` span with that id nests
   inside the root's interval (no orphans), with the phase durations
   summing to ≤ the root duration;
3. the router's ``req.route`` envelope span contains the root — the
   cross-hop join is a real containment, not two disconnected
   timelines;
4. the total-latency histogram carries the trace id as its exemplar.

Then the shed-storm dump smoke test: a ``max_queue=1`` replica with a
slow model is hammered until admission sheds past the storm
threshold, and the request flight recorder must produce a JSONL dump
whose records carry per-phase timings.

Accelerator-free: runs on the CPU backend in-process, like the other
gates in ci_check.sh.

Usage: JAX_PLATFORMS=cpu python scripts/check_request_tracing.py
Exit 0 = gate holds, 1 = a clause failed.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import urllib.error
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# storm knobs must be in the environment BEFORE the recorder builds
os.environ.setdefault("DL4J_TPU_REQREC_SHED_THRESHOLD", "5")
os.environ.setdefault("DL4J_TPU_REQREC_SHED_WINDOW_S", "30")
_TMP = tempfile.mkdtemp(prefix="dl4j_reqrec_gate_")
os.environ["DL4J_TPU_REQREC_DIR"] = _TMP

import numpy as np  # noqa: E402

TRACE_ID = "ci-gate-trace-0001"


def _mlp(seed):
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn.conf.builders import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _post(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json",
                 **(headers or {})})
    try:
        r = urllib.request.urlopen(req, timeout=60)
        return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _span_tree_clauses(failures):
    """Clauses 2-4: connected span tree + exemplar for TRACE_ID."""
    import time

    from deeplearning4j_tpu.common import telemetry

    # the replica handler emits its root span AFTER the response bytes
    # are on the wire, so a client that just read the body can race
    # it — poll briefly, like any async trace consumer
    deadline = time.monotonic() + 5.0
    while True:
        events = [e for e in telemetry.trace_events()
                  if e.get("args", {}).get("trace") == TRACE_ID]
        if (any(e["name"] == "request" for e in events)
                and any(e["name"] == "req.route" for e in events)) \
                or time.monotonic() >= deadline:
            break
        time.sleep(0.02)
    roots = [e for e in events if e["name"] == "request"]
    if len(roots) != 1:
        failures.append(f"expected exactly 1 'request' root span for "
                        f"the trace id, got {len(roots)}")
        return
    root = roots[0]
    r0, r1 = root["ts"], root["ts"] + root["dur"]
    phases = [e for e in events if e["name"].startswith("req.")
              and e["name"] != "req.route" and e.get("ph") == "X"]
    if not phases:
        failures.append("no req.<phase> spans under the root")
    #: chrome-trace timestamps are integer µs; allow 1ms of rounding
    slack = 1000
    for e in phases:
        if e["ts"] < r0 - slack or e["ts"] + e["dur"] > r1 + slack:
            failures.append(
                f"orphan phase span {e['name']}: "
                f"[{e['ts']}, {e['ts'] + e['dur']}] outside root "
                f"[{r0}, {r1}]")
    total_phase = sum(e["dur"] for e in phases)
    if total_phase > root["dur"] + slack:
        failures.append(
            f"phase durations sum to {total_phase}µs > root span "
            f"{root['dur']}µs")
    for want in ("req.admit", "req.queue", "req.device",
                 "req.serialize"):
        if not any(e["name"] == want for e in phases):
            failures.append(f"missing phase span {want}")
    routes = [e for e in events if e["name"] == "req.route"]
    if len(routes) != 1:
        failures.append(f"expected exactly 1 req.route envelope span, "
                        f"got {len(routes)}")
    else:
        q0, q1 = routes[0]["ts"], routes[0]["ts"] + routes[0]["dur"]
        if r0 < q0 - slack or r1 > q1 + slack:
            failures.append(
                f"req.route [{q0}, {q1}] does not contain the "
                f"request root [{r0}, {r1}]")
    ex = telemetry.histogram(
        "dl4j_serving_total_seconds").exemplar_of(model="gate")
    if not ex or ex["labels"].get("trace_id") != TRACE_ID:
        failures.append(f"latency histogram exemplar does not carry "
                        f"the trace id (got {ex!r})")


class _SlowModel:
    def output(self, x):
        import time
        x = np.asarray(x)
        time.sleep(0.05)
        return x[:, :1]


def _storm_clause(failures):
    """Shed-storm dump smoke test: a max_queue=1 replica under a
    burst of concurrent requests must shed past the storm threshold
    and the flight recorder must dump records with phase timings."""
    from deeplearning4j_tpu.serving import reqrec
    from deeplearning4j_tpu.serving.admission import \
        AdmissionController
    from deeplearning4j_tpu.serving.registry import ModelRegistry
    from deeplearning4j_tpu.serving.server import InferenceServer

    registry = ModelRegistry(default_buckets=(8,))
    registry.register("stormy", _SlowModel())
    srv = InferenceServer(registry,
                          AdmissionController(max_queue=1)).start(0)
    url = f"{srv.url}/v1/models/stormy:predict"
    payload = {"inputs": np.zeros((1, 8), np.float32).tolist()}
    codes = []
    lock = threading.Lock()

    def client():
        for _ in range(4):
            code, _, _ = _post(url, payload)
            with lock:
                codes.append(code)

    threads = [threading.Thread(target=client) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    srv.stop(drain=False)
    registry.shutdown()

    sheds = sum(1 for c in codes if c == 429)
    if sheds < int(os.environ["DL4J_TPU_REQREC_SHED_THRESHOLD"]):
        failures.append(f"storm did not materialize: only {sheds} "
                        f"sheds across {len(codes)} requests")
        return
    dumps = [f for f in os.listdir(_TMP)
             if "shed_storm" in f and f.endswith(".jsonl")]
    if not dumps:
        failures.append(f"no shed_storm dump in {_TMP} after "
                        f"{sheds} sheds")
        return
    with open(os.path.join(_TMP, sorted(dumps)[0])) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    meta, records = lines[0], lines[1:]
    if meta.get("reason") != "shed_storm":
        failures.append(f"dump meta reason {meta.get('reason')!r}")
    timed = [r for r in records if r.get("phase_ms")]
    if not timed:
        failures.append("shed_storm dump has no request records with "
                        "phase timings")
    else:
        print(f"request-tracing gate: storm dump holds "
              f"{len(records)} records ({len(timed)} with phase "
              f"timings) after {sheds} sheds")
    del reqrec  # imported for its side registration only


def main() -> int:
    from deeplearning4j_tpu.serving import ServingRouter

    failures = []
    router = ServingRouter(n_replicas=2, default_buckets=(8,),
                           health_interval_s=0.5)
    router.start(0)
    try:
        router.rollout("gate", lambda: _mlp(42), warmup_shape=(8,),
                       latency_slo_ms=500.0)
        x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
        code, body, headers = _post(
            f"{router.url}/v1/models/gate:predict",
            {"inputs": x.tolist()},
            headers={"X-Dl4j-Trace-Id": TRACE_ID})
        if code != 200:
            failures.append(f"traced predict returned {code}: "
                            f"{body[:120]!r}")
        if headers.get("X-Dl4j-Trace-Id") != TRACE_ID:
            failures.append(
                f"response did not echo the trace id (got "
                f"{headers.get('X-Dl4j-Trace-Id')!r})")
        rep = headers.get("X-Dl4j-Replica", "")
        if not rep.startswith("replica-"):
            failures.append(f"response missing X-Dl4j-Replica "
                            f"(got {rep!r})")
        if not failures:
            _span_tree_clauses(failures)
            print(f"request-tracing gate: one traced predict through "
                  f"router->{rep} produced a connected span tree "
                  f"under trace {TRACE_ID}")
    finally:
        router.stop(drain=False, timeout=10)

    _storm_clause(failures)

    if failures:
        for f in failures[:10]:
            print(f"FAIL: {f}")
        return 1
    print("OK: span tree connected (phases nest in the root, root "
          "nests in req.route, durations consistent), trace id on "
          "response + exemplar, shed storm dumped the flight "
          "recorder")
    return 0


if __name__ == "__main__":
    sys.exit(main())
