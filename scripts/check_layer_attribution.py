#!/usr/bin/env python
"""CI conformance gate for the layer-attribution observatory.

Builds LeNet (MultiLayerNetwork) and BERT-tiny, runs
``model.layer_report()`` on each, and asserts the contract the
observatory sells:

1. reconcile: per-layer flops/bytes sums match the whole-model
   ``cost_analysis()`` totals within 1%;
2. coverage: at least half of the model's flops land on named layer
   scopes (bytes coverage is reported but not gated — scan-carry and
   optimizer plumbing legitimately dominate bytes on small models);
3. presence: every ``layer_i`` of the LeNet stack appears in the
   report, forward AND backward flops attributed.

Exit 0 = conformant, 1 = violation (messages on stdout), runs on the
CPU backend in well under a minute.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

RECONCILE_MAX_PCT = 1.0
FLOPS_COVERAGE_MIN = 0.5


def _check(report, name, fails):
    from deeplearning4j_tpu.common import layerprof
    err = layerprof.reconcile_error_pct(report)
    cov = report["coverage"]
    print(f"{name}: reconcile_err={err:.4f}% "
          f"coverage flops={cov['flops']} bytes={cov['bytes']} "
          f"layers={len(report['layers'])}")
    if err > RECONCILE_MAX_PCT:
        fails.append(f"{name}: per-layer sums diverge from "
                     f"cost_analysis by {err:.2f}% "
                     f"(max {RECONCILE_MAX_PCT}%)")
    if cov["flops"] < FLOPS_COVERAGE_MIN:
        fails.append(f"{name}: flops coverage {cov['flops']} below "
                     f"{FLOPS_COVERAGE_MIN} — layer scopes are not "
                     f"reaching the compiled HLO")


def _lenet(fails):
    import numpy as np

    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning import Adam
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn import (InputType, MultiLayerNetwork,
                                       NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                                   DenseLayer,
                                                   OutputLayer,
                                                   PoolingType,
                                                   SubsamplingLayer)

    conf = (NeuralNetConfiguration.Builder()
            .seed(1).updater(Adam(1e-3)).list()
            .layer(ConvolutionLayer.Builder(5, 5).n_out(20)
                   .activation(Activation.IDENTITY).build())
            .layer(SubsamplingLayer.Builder(PoolingType.MAX)
                   .kernel_size((2, 2)).stride((2, 2)).build())
            .layer(ConvolutionLayer.Builder(5, 5).n_out(50)
                   .activation(Activation.IDENTITY).build())
            .layer(SubsamplingLayer.Builder(PoolingType.MAX)
                   .kernel_size((2, 2)).stride((2, 2)).build())
            .layer(DenseLayer.Builder().n_out(500)
                   .activation(Activation.RELU).build())
            .layer(OutputLayer.Builder(
                LossFunction.NEGATIVELOGLIKELIHOOD)
                   .n_out(10).activation(Activation.SOFTMAX).build())
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 784)).astype("float32")
    y = np.eye(10, dtype="float32")[rng.integers(0, 10, 8)]
    report = net.layer_report(x, y)
    _check(report, "lenet", fails)
    for i in range(6):
        name = f"layer_{i}"
        ent = report["layers"].get(name)
        if ent is None:
            fails.append(f"lenet: {name} missing from the report")
        elif ent["flops_fwd"] <= 0 or ent["flops_bwd"] <= 0:
            fails.append(f"lenet: {name} fwd/bwd flops not both "
                         f"attributed (fwd={ent['flops_fwd']}, "
                         f"bwd={ent['flops_bwd']})")


def _bert(fails):
    import numpy as np

    from deeplearning4j_tpu.learning.updaters import Adam
    from deeplearning4j_tpu.models.bert import Bert, BertConfig

    conf = BertConfig.tiny(compute_dtype="bfloat16",
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0)
    model = Bert(conf, Adam(1e-4)).init()
    rng = np.random.default_rng(0)
    batch = {
        "input_ids": rng.integers(0, conf.vocab_size, (4, 64)),
        "mlm_labels": rng.integers(0, conf.vocab_size, (4, 64)),
    }
    report = model.layer_report(batch)
    _check(report, "bert-tiny", fails)
    for scope in ("embeddings", "encoder.attention", "encoder.ffn",
                  "mlm_head"):
        if scope not in report["layers"]:
            fails.append(f"bert-tiny: scope {scope!r} missing from "
                         f"the report")


def main() -> int:
    fails: list = []
    _lenet(fails)
    _bert(fails)
    if fails:
        for f in fails:
            print(f"FAIL: {f}")
        return 1
    print("layer-attribution conformance: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
