#!/usr/bin/env python
"""CI gate: the serving-SLO contract under load and rollout.

Boots a 2-replica :class:`ServingRouter` on a toy model and hammers
it with concurrent clients while a fleet-wide warm-then-drain rollout
replaces the live version. The gate holds the ISSUE-15 acceptance
bar:

1. every response is either a 200 whose outputs match v1's or v2's
   dense math bitwise, or a well-formed shed (429/503 carrying a
   positive integer ``Retry-After``) — nothing is dropped, no 5xx
   surprises, no connection resets;
2. zero post-warmup retraces on every replica's live version (the
   shape-bucketed warmup covered every flush the load produced);
3. the rollout completed on every replica (live version == 2
   fleet-wide) while the load was running.

Accelerator-free: runs on the CPU backend in-process, like the other
gates in ci_check.sh.

Usage: JAX_PLATFORMS=cpu python scripts/check_serving_slo.py
Exit 0 = gate holds, 1 = a clause failed.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import urllib.error
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

N_CLIENTS = 6
SECONDS_AFTER_ROLLOUT = 0.5


def _mlp(seed):
    from deeplearning4j_tpu.activations import Activation
    from deeplearning4j_tpu.learning.updaters import Sgd
    from deeplearning4j_tpu.lossfunctions import LossFunction
    from deeplearning4j_tpu.nn.conf.builders import \
        NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.Builder()
            .seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_in=8, n_out=16,
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=3,
                               loss_function=LossFunction.MCXENT,
                               activation=Activation.SOFTMAX))
            .set_input_type(InputType.feed_forward(8))
            .build())
    return MultiLayerNetwork(conf).init()


def _post(base, payload):
    req = urllib.request.Request(
        f"{base}/v1/models/gate:predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        r = urllib.request.urlopen(req, timeout=60)
        return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def main() -> int:
    from deeplearning4j_tpu.serving import ServingRouter

    net1, net2 = _mlp(42), _mlp(99)
    x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    ref1 = np.asarray(net1.output(x))
    ref2 = np.asarray(net2.output(x))

    router = ServingRouter(n_replicas=2, default_buckets=(8,),
                           health_interval_s=0.5)
    router.start(0)
    failures = []
    try:
        router.rollout("gate", lambda: _mlp(42), warmup_shape=(8,),
                       latency_slo_ms=500.0)
        results, errors = [], []
        stop = threading.Event()

        def client():
            while not stop.is_set():
                try:
                    results.append(_post(router.url,
                                         {"inputs": x.tolist()}))
                except Exception as e:      # noqa: BLE001
                    errors.append(repr(e))

        threads = [threading.Thread(target=client)
                   for _ in range(N_CLIENTS)]
        for t in threads:
            t.start()
        try:
            router.rollout("gate", lambda: _mlp(99),
                           warmup_shape=(8,), latency_slo_ms=500.0)
            stop.wait(SECONDS_AFTER_ROLLOUT)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)

        ok200 = shed = 0
        for code, body, headers in results:
            if code == 200:
                ok200 += 1
                got = np.asarray(json.loads(body)["outputs"],
                                 dtype=np.float32)
                if not (np.array_equal(got, ref1)
                        or np.array_equal(got, ref2)):
                    failures.append(
                        f"200 response matched neither version "
                        f"(first row {got[0]!r})")
            elif code in (429, 503):
                shed += 1
                ra = headers.get("Retry-After")
                if not (ra and ra.isdigit() and int(ra) >= 1):
                    failures.append(
                        f"shed {code} without a well-formed "
                        f"Retry-After (got {ra!r})")
            else:
                failures.append(f"unexpected status {code}: "
                                f"{body[:120]!r}")
        if errors:
            failures.append(f"{len(errors)} dropped/raised requests "
                            f"(first: {errors[0]})")
        if ok200 == 0:
            failures.append("no successful responses at all")
        for rep in router.replicas:
            ver = rep.registry.model("gate")
            if ver.version != 2:
                failures.append(f"{rep.name}: rollout did not land "
                                f"(live version {ver.version})")
            retr = ver.retraces_since_warmup()
            if retr != 0:
                failures.append(f"{rep.name}: {retr} post-warmup "
                                f"retrace(s)")
        print(f"serving-SLO gate: {len(results)} requests across a "
              f"live rollout -> {ok200} ok, {shed} shed "
              f"(Retry-After well-formed), "
              f"{len(errors)} dropped; retraces after warmup: 0 "
              f"expected on 2 replicas")
    finally:
        router.stop(drain=False, timeout=10)

    if failures:
        for f in failures[:10]:
            print(f"FAIL: {f}")
        return 1
    print("OK: every response was a bitwise-correct 200 or a "
          "well-formed shed; zero retraces; rollout hitless")
    return 0


if __name__ == "__main__":
    sys.exit(main())
