#!/usr/bin/env python
"""Keep the README metrics catalog honest.

Scans the source tree for telemetry metric registrations
(``telemetry.counter("dl4j_...")`` / ``gauge`` / ``histogram`` — and
the registry-method spellings) and fails if any registered ``dl4j_*``
metric name is missing from the README "Observability" catalog, or if
the catalog documents a metric no code registers (stale docs are as
misleading as missing ones).

Runs as a tier-1 test (tests/test_telemetry.py) and standalone:

    python scripts/check_telemetry_catalog.py
"""
from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
README = ROOT / "README.md"

#: metric registrations: counter("name" / gauge("name" /
#: histogram("name" — any receiver (telemetry module, a registry, or
#: the module-level helpers called bare inside telemetry.py)
_REG_RE = re.compile(
    r"\b(?:counter|gauge|histogram)\(\s*\n?\s*['\"](dl4j_[a-z0-9_]+)")

#: names prefixed dl4j_ anywhere in the README catalog section
_DOC_RE = re.compile(r"`(dl4j_[a-z0-9_]+)`")

#: registrations that are deliberately NOT part of the public catalog
_EXEMPT = {"dl4j_bench_counter_total", "dl4j_bench_hist_seconds"}


def registered_metrics() -> set:
    names = set()
    for base in ("deeplearning4j_tpu", "benchmarks", "scripts"):
        for p in (ROOT / base).rglob("*.py"):
            names.update(_REG_RE.findall(p.read_text()))
    names.update(_REG_RE.findall((ROOT / "bench.py").read_text()))
    return names - _EXEMPT


def documented_metrics() -> set:
    text = README.read_text()
    m = re.search(r"## Observability(.*?)(?:\n## |\Z)", text, re.S)
    if not m:
        return set()
    return set(_DOC_RE.findall(m.group(1)))


def main() -> int:
    reg = registered_metrics()
    doc = documented_metrics()
    rc = 0
    missing = sorted(reg - doc)
    stale = sorted(doc - reg)
    if not doc:
        print("FAIL: README has no '## Observability' catalog section")
        rc = 1
    if missing:
        print("FAIL: metrics registered in code but missing from the "
              "README Observability catalog:")
        for n in missing:
            print(f"  - {n}")
        rc = 1
    if stale:
        print("FAIL: metrics documented in the README catalog but "
              "registered nowhere in code:")
        for n in stale:
            print(f"  - {n}")
        rc = 1
    if rc == 0:
        print(f"OK: {len(reg)} registered metrics all documented, "
              f"no stale catalog entries")
    return rc


if __name__ == "__main__":
    sys.exit(main())
