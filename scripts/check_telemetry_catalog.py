#!/usr/bin/env python
"""Keep the README metrics catalog honest.

Scans the source tree — every ``deeplearning4j_tpu`` subpackage
(including ``serving/``), ``benchmarks/``, ``scripts/``,
``examples/``, and ``bench.py`` — for telemetry metric registrations
(``telemetry.counter("dl4j_...")`` / ``gauge`` / ``histogram`` — and
the registry-method spellings) and fails if:

- a registered ``dl4j_*`` metric is missing from the README
  "Observability" catalog,
- the catalog documents a metric no code registers (stale docs are as
  misleading as missing ones), or
- the catalog's Type column disagrees with the registration kind
  (a counter documented as a gauge sends scrapers down the wrong
  rate()/delta() path).

Runs as a tier-1 test (tests/test_telemetry.py) and standalone:

    python scripts/check_telemetry_catalog.py
"""
from __future__ import annotations

import pathlib
import re
import sys
from typing import Dict, Set

ROOT = pathlib.Path(__file__).resolve().parent.parent
README = ROOT / "README.md"

#: metric registrations: counter("name" / gauge("name" /
#: histogram("name" — any receiver (telemetry module, a registry, or
#: the module-level helpers called bare inside telemetry.py)
_REG_RE = re.compile(
    r"\b(counter|gauge|histogram)\(\s*\n?\s*['\"](dl4j_[a-z0-9_]+)")

#: names prefixed dl4j_ anywhere in the README catalog section
_DOC_RE = re.compile(r"`(dl4j_[a-z0-9_]+)`")

#: catalog table rows: | `name` | kind | ...
_DOC_ROW_RE = re.compile(
    r"^\|\s*`(dl4j_[a-z0-9_]+)`\s*\|\s*(counter|gauge|histogram)\s*\|",
    re.M)

#: registrations that are deliberately NOT part of the public catalog
_EXEMPT = {"dl4j_bench_counter_total", "dl4j_bench_hist_seconds"}

_SCAN_BASES = ("deeplearning4j_tpu", "benchmarks", "scripts",
               "examples")


def registered_metrics() -> Dict[str, Set[str]]:
    """{metric name: {registration kinds seen}} across the tree."""
    names: Dict[str, Set[str]] = {}
    texts = []
    for base in _SCAN_BASES:
        texts.extend(p.read_text()
                     for p in (ROOT / base).rglob("*.py"))
    texts.append((ROOT / "bench.py").read_text())
    for text in texts:
        for kind, name in _REG_RE.findall(text):
            if name not in _EXEMPT:
                names.setdefault(name, set()).add(kind)
    return names


def documented_metrics() -> Dict[str, str]:
    """{metric name: documented kind} from the catalog tables in the
    "## Observability", "## Diagnostics", "## Scaling observatory",
    "## Layer attribution" and "## Fault tolerance & elasticity"
    sections (names mentioned outside table rows count as documented
    with kind '')."""
    text = README.read_text()
    doc: Dict[str, str] = {}
    for heading in ("Observability", "Diagnostics",
                    "Scaling observatory", "Layer attribution",
                    "Fault tolerance & elasticity"):
        m = re.search(rf"## {heading}(.*?)(?:\n## |\Z)", text, re.S)
        if not m:
            continue
        section = m.group(1)
        for name in _DOC_RE.findall(section):
            doc.setdefault(name, "")
        doc.update({name: kind
                    for name, kind in _DOC_ROW_RE.findall(section)})
    return doc


def main() -> int:
    reg = registered_metrics()
    doc = documented_metrics()
    rc = 0
    missing = sorted(set(reg) - set(doc))
    stale = sorted(set(doc) - set(reg))
    if not doc:
        print("FAIL: README has no '## Observability' catalog section")
        rc = 1
    if missing:
        print("FAIL: metrics registered in code but missing from the "
              "README Observability catalog:")
        for n in missing:
            print(f"  - {n}")
        rc = 1
    if stale:
        print("FAIL: metrics documented in the README catalog but "
              "registered nowhere in code:")
        for n in stale:
            print(f"  - {n}")
        rc = 1
    kind_clash = sorted(
        (n, kinds, doc[n]) for n, kinds in reg.items()
        if doc.get(n) and doc[n] not in kinds)
    if kind_clash:
        print("FAIL: catalog Type column disagrees with the "
              "registration kind:")
        for n, kinds, documented in kind_clash:
            print(f"  - {n}: registered {sorted(kinds)}, "
                  f"documented {documented!r}")
        rc = 1
    if rc == 0:
        print(f"OK: {len(reg)} registered metrics all documented with "
              f"matching types, no stale catalog entries")
    return rc


if __name__ == "__main__":
    sys.exit(main())
