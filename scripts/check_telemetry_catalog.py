#!/usr/bin/env python
"""Keep the README metrics catalog honest.

Thin CLI wrapper over the metric-registry lint rule
(``scripts/dl4j_lint/rules_metric.py``) — the scanning logic lives
there, shared with ``python -m scripts.dl4j_lint``. This entry point
keeps the historical contract for ci_check gate 1 and the tier-1 test
(tests/test_telemetry.py): the same FAIL/OK lines, exit 0 iff the
catalog matches the code.

Scans the source tree — every ``deeplearning4j_tpu`` subpackage
(including ``serving/``), ``benchmarks/``, ``scripts/``,
``examples/``, and ``bench.py`` — for telemetry metric registrations
(``telemetry.counter("dl4j_...")`` / ``gauge`` / ``histogram`` — and
the registry-method spellings) and fails if:

- a registered ``dl4j_*`` metric is missing from the README
  "Observability" catalog,
- the catalog documents a metric no code registers (stale docs are as
  misleading as missing ones), or
- the catalog's Type column disagrees with the registration kind
  (a counter documented as a gauge sends scrapers down the wrong
  rate()/delta() path).

Runs as a tier-1 test (tests/test_telemetry.py) and standalone:

    python scripts/check_telemetry_catalog.py
"""
from __future__ import annotations

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def main() -> int:
    sys.path.insert(0, str(ROOT))
    from scripts.dl4j_lint.core import build_repo_context
    from scripts.dl4j_lint.rules_metric import (documented_metrics,
                                                registered_metrics)

    repo = build_repo_context(ROOT)
    reg = registered_metrics(repo)
    doc = documented_metrics(repo.readme())
    rc = 0
    missing = sorted(set(reg) - set(doc))
    stale = sorted(set(doc) - set(reg))
    if not doc:
        print("FAIL: README has no '## Observability' catalog section")
        rc = 1
    if missing:
        print("FAIL: metrics registered in code but missing from the "
              "README Observability catalog:")
        for n in missing:
            print(f"  - {n}")
        rc = 1
    if stale:
        print("FAIL: metrics documented in the README catalog but "
              "registered nowhere in code:")
        for n in stale:
            print(f"  - {n}")
        rc = 1
    kind_clash = sorted(
        (n, kinds, doc[n]) for n, (kinds, _, _) in reg.items()
        if doc.get(n) and doc[n] not in kinds)
    if kind_clash:
        print("FAIL: catalog Type column disagrees with the "
              "registration kind:")
        for n, kinds, documented in kind_clash:
            print(f"  - {n}: registered {sorted(kinds)}, "
                  f"documented {documented!r}")
        rc = 1
    if rc == 0:
        print(f"OK: {len(reg)} registered metrics all documented with "
              f"matching types, no stale catalog entries")
    return rc


if __name__ == "__main__":
    sys.exit(main())
