"""Benchmark: ResNet-50 training throughput, images/sec/chip.

BASELINE.md metric #2 (single-chip leg of the north star). Synthetic
ImageNet-shaped data, pre-placed on device (the metric is compute
throughput; the input pipeline is benchmarked separately — and on this
rig the host→device hop crosses a network tunnel, which would swamp
the measurement). Mixed precision: bfloat16 compute with float32
master params — the MXU-native configuration.

`BASELINE.json.published` is empty — no reference number exists, so
``vs_baseline`` is reported as 1.0 until a reference measurement lands
(BASELINE.md measurement protocol step 4).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np

_ROOT = os.path.dirname(os.path.abspath(__file__))


def main():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.zoo import ResNet50

    on_tpu = jax.devices()[0].platform == "tpu"
    batch = 256 if on_tpu else 8     # 256 ≈ +15% over 128 on v5e
    hw = 224 if on_tpu else 64

    net = ResNet50(num_classes=1000, height=hw, width=hw,
                   compute_dtype="bfloat16").init()

    rng = np.random.RandomState(0)
    x = rng.randn(batch, hw, hw, 3).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, batch)]
    # device-resident batch: measure the train step, not the tunnel
    ds = DataSet(jax.device_put(jnp.asarray(x)),
                 jax.device_put(jnp.asarray(y)))

    steps = 60 if on_tpu else 3
    # fit_steps: `steps` iterations per dispatch (steps_per_execution),
    # removing the per-step host dispatch gap (~+13% at this shape)
    net.fit_steps(ds, steps)     # warmup (compile)
    jax.block_until_ready(net.params)
    float(net.score())

    from benchmarks.timing import median_throughput

    def run_once():
        net.fit_steps(ds, steps)
        jax.block_until_ready(net.params)
        # score() syncs on the final step's loss — guarantees the whole
        # dispatch chain actually executed before we stop the clock
        # (the sync lives OUTSIDE the assert: python -O must not
        # remove it)
        s = float(net.score())
        assert np.isfinite(s)

    stats = median_throughput(run_once, steps * batch,
                              n_trials=5 if on_tpu else 3)
    ips = stats["value"]
    line = {
        "metric": "resnet50_train_throughput"
                  + ("" if on_tpu else f"_cpu_proxy_{hw}px"),
        **stats,
        "unit": "images/sec/chip",
        "vs_baseline": 1.0,
    }
    # provenance stamp: schema version, git rev, jax version, device
    # kind/count, DL4J_TPU_* env — BENCH_r*.json trajectories are only
    # comparable when the rig that produced them is on record
    try:
        from deeplearning4j_tpu.common import diagnostics
        line["meta"] = diagnostics.bench_meta()
        # top-level proxy marker: a CPU-proxy round and a TPU round
        # are not comparable — check_bench_regression.py refuses to
        # diff across a flip of this flag
        line["meta"]["proxy"] = not on_tpu
    except Exception as e:
        print(f"meta block failed: {e!r}", file=sys.stderr)
    # Roofline evidence (BENCH_notes_r02.md): XLA cost analysis of the
    # optimized train step (shared helper; flops are a floor), run
    # through the automatic classifier (which roof binds, % of it).
    try:
        from benchmarks.cost_util import (V5E_BF16_PEAK_TFLOPS,
                                          V5E_HBM_GBPS, graph_step_cost)
        from deeplearning4j_tpu.common import diagnostics
        flops, byts = graph_step_cost(net, x, y)
        step_s = batch / ips
        roof = diagnostics.roofline(
            flops, byts, step_s,
            peak_tflops=V5E_BF16_PEAK_TFLOPS if on_tpu else None,
            peak_hbm_gbps=V5E_HBM_GBPS if on_tpu else None)
        # keep the historical top-level keys (r02+ trajectory) AND the
        # full classification
        line["tflops"] = round(roof["tflops"], 1)
        if on_tpu:
            line["pct_bf16_peak"] = roof["pct_compute_peak"]
            line["pct_hbm_peak"] = roof["pct_hbm_peak"]
        line["roofline"] = roof
    except Exception as e:
        print(f"roofline block failed: {e!r}", file=sys.stderr)
    # HBM attribution: where the bytes actually live after the run —
    # device allocator live/peak plus per-buffer accounting (params /
    # updater state / staging / activations+workspace residual)
    try:
        from deeplearning4j_tpu.common import diagnostics
        line["memory"] = diagnostics.memory_report(net)
    except Exception as e:
        print(f"memory block failed: {e!r}", file=sys.stderr)
    # Scaling-observatory breakdown: where the run's step time went
    # (data_wait / compute / collective / updater / host_sync /
    # checkpoint_stall) — phase means sum to ~the mean step time, so a
    # future throughput regression comes pre-attributed to a phase.
    try:
        from deeplearning4j_tpu.common import stepstats
        bd = stepstats.collector().summary()
        if bd.get("steps"):
            line["step_breakdown"] = bd
    except Exception as e:
        print(f"step-breakdown block failed: {e!r}", file=sys.stderr)
    # exercise the pod scaling harness's REAL clock path at n=1 (the
    # round-2 verdict asked that parallel/scaling.py time something
    # real before it is trusted on a pod); small shape — this checks
    # the machinery, not the headline number
    try:
        from deeplearning4j_tpu.datasets.dataset import DataSet as DS
        from deeplearning4j_tpu.models.zoo import LeNet
        from deeplearning4j_tpu.parallel.scaling import \
            measure_dp_scaling

        def _mk_batch(n):
            r = np.random.RandomState(1)
            return DS(r.randn(n, 28, 28, 1).astype(np.float32),
                      np.eye(10, dtype=np.float32)[
                          r.randint(0, 10, n)])

        sizes = (1,) if not on_tpu else tuple(sorted(
            {1, len(jax.devices())}))
        rep = measure_dp_scaling(
            lambda: LeNet(num_classes=10).init(), _mk_batch, sizes,
            per_chip_batch=64, steps=5, warmup=1)
        # clock-path CANARY, not a throughput: 5 LeNet steps through
        # the axon tunnel are dispatch-dominated (r3 verdict Weak #4
        # — the old name scaling_n1_ips invited misreading)
        line["scaling_harness_canary_ips"] = round(
            rep["throughput"][1], 1)
        # the ROADMAP item-2 `scaling` block: per-chip throughput and
        # efficiency at each mesh size vs the smallest-size baseline,
        # with the cross-host observatory's skew report when a
        # SharedTrainingMaster leader ran one (single host: zero skew)
        from deeplearning4j_tpu.common import stepstats
        line["scaling"] = stepstats.scaling_block(rep)
        # wire-cost context for the efficiency curve: what one step's
        # update exchange moves per replica at the largest mesh size
        from deeplearning4j_tpu.parallel import zero
        line["scaling"]["update_exchange"] = zero.exchange_report(
            LeNet(num_classes=10).init().params, max(sizes))
    except Exception as e:
        print(f"scaling-harness leg failed: {e!r}", file=sys.stderr)
    # CPU-proxy pipeline overhead, every round (round-2 verdict Weak
    # #3: regressions in the host data-path software must be caught
    # even though the axon tunnel makes the on-rig e2e number
    # bandwidth-bound). Subprocess on the CPU backend.
    try:
        env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "benchmarks", "bench_pipeline.py")],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_ROOT)
        if out.returncode != 0:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-400:]}")
        for ln in out.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue              # tolerate library banners
            rec = json.loads(ln)
            if rec["metric"].startswith("input_pipeline_overhead"):
                line["pipeline_overhead_cpu_proxy_pct"] = rec["value"]
        if "pipeline_overhead_cpu_proxy_pct" not in line:
            print("pipeline-proxy leg: no overhead line in child "
                  "output", file=sys.stderr)
    except Exception as e:
        print(f"pipeline-proxy leg failed: {e!r}", file=sys.stderr)
    # Feeding-ladder leg: per-step input-pipeline stall under the
    # three feeding modes (sync / host-async / device-prefetch), so
    # BENCH_*.json rounds track feeding overhead alongside throughput.
    # CPU-proxy subprocess, like the pipeline leg above.
    try:
        env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "benchmarks",
                          "bench_input_pipeline.py")],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_ROOT)
        if out.returncode != 0:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-400:]}")
        for ln in out.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue              # tolerate library banners
            rec = json.loads(ln)
            if rec["metric"] == "input_pipeline_stall_pct":
                line["input_pipeline_stall_pct"] = rec["value"]
                line["input_pipeline_stall_sync_pct"] = rec["sync_pct"]
                line["input_pipeline_stall_host_async_pct"] = \
                    rec["host_async_pct"]
        if "input_pipeline_stall_pct" not in line:
            print("feeding-ladder leg: no stall line in child output",
                  file=sys.stderr)
    except Exception as e:
        print(f"feeding-ladder leg failed: {e!r}", file=sys.stderr)
    # Serving leg: batcher latency percentiles vs batch window + the
    # warm/cold first-request gap (the shape-bucketed-warmup payoff).
    # CPU-proxy subprocess, like the pipeline legs above.
    try:
        env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "benchmarks", "bench_serving.py")],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_ROOT)
        if out.returncode != 0:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-400:]}")
        for ln in out.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue              # tolerate library banners
            rec = json.loads(ln)
            if rec.get("metric") == "serving_latency":
                rec.pop("metric")
                line["serving"] = rec
        if "serving" not in line:
            print("serving leg: no latency line in child output",
                  file=sys.stderr)
    except Exception as e:
        print(f"serving leg failed: {e!r}", file=sys.stderr)
    # Generative leg: paged-KV decode goodput, streaming TTFT /
    # inter-token percentiles, pool occupancy vs shed rate, and the
    # paged-vs-dense decode-attention A/B. CPU-proxy subprocess, like
    # the serving leg above.
    try:
        env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "benchmarks", "bench_generative.py")],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_ROOT)
        if out.returncode != 0:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-400:]}")
        for ln in out.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue              # tolerate library banners
            rec = json.loads(ln)
            if rec.get("metric") == "generative":
                rec.pop("metric")
                line["generative"] = rec
        if "generative" not in line:
            print("generative leg: no line in child output",
                  file=sys.stderr)
    except Exception as e:
        print(f"generative leg failed: {e!r}", file=sys.stderr)
    # Update-sharding leg: ZeRO-1 sharded vs dense exchange — per-chip
    # updater-state residency + step time, and the accumulation-window
    # micro-step times. CPU-proxy subprocess on the virtual 8-device
    # mesh, like the legs above.
    try:
        env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "benchmarks",
                          "bench_update_sharding.py")],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_ROOT)
        if out.returncode != 0:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-400:]}")
        for ln in out.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue              # tolerate library banners
            rec = json.loads(ln)
            if rec.get("metric") == "update_sharding":
                rec.pop("metric")
                line["update_sharding"] = rec
        if "update_sharding" not in line:
            print("update-sharding leg: no line in child output",
                  file=sys.stderr)
    except Exception as e:
        print(f"update-sharding leg failed: {e!r}", file=sys.stderr)
    # FSDP leg: ZeRO-3 vs ZeRO-1 vs dense — per-chip param + updater-
    # state residency and step time, plus the fsdp accumulation-window
    # micro-step times. CPU-proxy subprocess on the virtual 8-device
    # mesh, like the legs above.
    try:
        env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "benchmarks", "bench_fsdp.py")],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_ROOT)
        if out.returncode != 0:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-400:]}")
        for ln in out.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue              # tolerate library banners
            rec = json.loads(ln)
            if rec.get("metric") == "fsdp":
                rec.pop("metric")
                line["fsdp"] = rec
        if "fsdp" not in line:
            print("fsdp leg: no line in child output", file=sys.stderr)
    except Exception as e:
        print(f"fsdp leg failed: {e!r}", file=sys.stderr)
    # 2D-parallelism leg: (data x model) and (fsdp x model) training
    # modes vs dp-only — per-mode step time, per-axis update wire
    # bytes (the model axis must move zero), and per-chip residency.
    # CPU-proxy subprocess on the virtual 8-device mesh, like the
    # legs above.
    try:
        env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "benchmarks", "bench_2d.py")],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_ROOT)
        if out.returncode != 0:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-400:]}")
        for ln in out.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue              # tolerate library banners
            rec = json.loads(ln)
            if rec.get("metric") == "scaling_2d":
                rec.pop("metric")
                line["scaling_2d"] = rec
        if "scaling_2d" not in line:
            print("2d leg: no line in child output", file=sys.stderr)
    except Exception as e:
        print(f"2d leg failed: {e!r}", file=sys.stderr)
    # Pipeline-parallelism leg: the promoted pp fit path — analytic
    # bubble-vs-n_micro sweep, gpipe-vs-1f1b peak activation
    # residency, and measured pp2 / pp2xdp2 step time + stage idle.
    # CPU-proxy subprocess on the virtual 8-device mesh, like the
    # legs above.
    try:
        env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "benchmarks", "bench_pipeline.py"),
             "--pp"],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_ROOT)
        if out.returncode != 0:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-400:]}")
        for ln in out.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue              # tolerate library banners
            rec = json.loads(ln)
            if rec.get("metric") == "pipeline":
                rec.pop("metric")
                line["pipeline"] = rec
        if "pipeline" not in line:
            print("pipeline leg: no line in child output",
                  file=sys.stderr)
    except Exception as e:
        print(f"pipeline leg failed: {e!r}", file=sys.stderr)
    # Fault-tolerance leg: checkpoint step-loop stall (fully
    # synchronous vs deferred async snapshot) and warm-cache resume
    # latency — the costs the preemption/auto-resume machinery pays.
    # CPU-proxy subprocess, like the legs above.
    try:
        env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "benchmarks",
                          "bench_fault_tolerance.py")],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_ROOT)
        if out.returncode != 0:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-400:]}")
        for ln in out.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue              # tolerate library banners
            rec = json.loads(ln)
            if rec.get("metric") == "fault_tolerance":
                rec.pop("metric")
                line["fault_tolerance"] = rec
        if "fault_tolerance" not in line:
            print("fault-tolerance leg: no line in child output",
                  file=sys.stderr)
    except Exception as e:
        print(f"fault-tolerance leg failed: {e!r}", file=sys.stderr)
    # Graph-optimizer leg: per-pass rewrite counts + fused-vs-unfused
    # imported-BERT step time, and the flash-vs-dense compiled temp
    # memory floor at a long-sequence shape. CPU-proxy subprocess,
    # like the legs above.
    try:
        env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "benchmarks", "bench_graphopt.py")],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_ROOT)
        if out.returncode != 0:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-400:]}")
        for ln in out.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue              # tolerate library banners
            rec = json.loads(ln)
            if rec.get("metric") == "graph_optimizer":
                rec.pop("metric")
                line["graph_optimizer"] = rec
        if "graph_optimizer" not in line:
            print("graph-optimizer leg: no line in child output",
                  file=sys.stderr)
    except Exception as e:
        print(f"graph-optimizer leg failed: {e!r}", file=sys.stderr)
    # Conv-kernel leg: fused (DL4J_TPU_FUSED_CONV Pallas epilogue
    # family) vs unfused ResNet-bottleneck train step — step time,
    # compiled temp bytes, cost-analysis bytes, and pct_of_roof from
    # the roofline classifier. CPU-proxy subprocess (interpret-mode
    # kernels; the line carries meta.proxy).
    try:
        env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "benchmarks",
                          "bench_conv_kernels.py")],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_ROOT)
        if out.returncode != 0:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-400:]}")
        for ln in out.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue              # tolerate library banners
            rec = json.loads(ln)
            if rec.get("metric") == "conv_kernels":
                rec.pop("metric")
                line["conv_kernels"] = rec
        if "conv_kernels" not in line:
            print("conv-kernel leg: no line in child output",
                  file=sys.stderr)
    except Exception as e:
        print(f"conv-kernel leg failed: {e!r}", file=sys.stderr)
    # Long-context leg: the 8192/16384/32768 attention train-step
    # ladder (collapses to one seq-512 proxy point off-TPU), each
    # entry stamped with the kernel-select auto decision for its
    # nominal TPU shape.
    try:
        env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "benchmarks", "bench_longcontext.py"),
             "--sweep"],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_ROOT)
        if out.returncode != 0:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-400:]}")
        for ln in out.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue              # tolerate library banners
            rec = json.loads(ln)
            if rec.get("metric") == "longcontext":
                rec.pop("metric")
                line["longcontext"] = rec
        if "longcontext" not in line:
            print("long-context leg: no line in child output",
                  file=sys.stderr)
    except Exception as e:
        print(f"long-context leg failed: {e!r}", file=sys.stderr)
    # Layer-attribution leg: per-layer time/flops/bytes roofline with
    # the kernel-select decision join, on ResNet-50 + BERT-tiny — the
    # top-k layers each round so a regression comes pre-attributed to
    # a layer. CPU-proxy subprocess, like the legs above.
    try:
        env = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "benchmarks",
                          "bench_layer_attribution.py")],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=_ROOT)
        if out.returncode != 0:
            raise RuntimeError(
                f"rc={out.returncode}: {out.stderr.strip()[-400:]}")
        for ln in out.stdout.strip().splitlines():
            if not ln.startswith("{"):
                continue              # tolerate library banners
            rec = json.loads(ln)
            if rec.get("metric") == "layer_attribution":
                rec.pop("metric")
                line["layer_attribution"] = rec
        if "layer_attribution" not in line:
            print("layer-attribution leg: no line in child output",
                  file=sys.stderr)
    except Exception as e:
        print(f"layer-attribution leg failed: {e!r}", file=sys.stderr)
    # Telemetry panel: the registry the run's hot paths recorded into
    # (train-step histogram, compile-cache counters, prefetch stats
    # when an iterator fed) — the same data /metrics would serve.
    try:
        from deeplearning4j_tpu.common.telemetry import MetricsRegistry
        reg = MetricsRegistry.get()
        if reg.enabled:
            line["telemetry"] = reg.summary()
    except Exception as e:
        print(f"telemetry leg failed: {e!r}", file=sys.stderr)
    print(json.dumps(line))


if __name__ == "__main__":
    main()
