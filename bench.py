"""Benchmark: ResNet-50 training throughput, images/sec/chip.

BASELINE.md metric #2 (single-chip leg of the north star). Synthetic
ImageNet-shaped data, pre-placed on device (the metric is compute
throughput; the input pipeline is benchmarked separately — and on this
rig the host→device hop crosses a network tunnel, which would swamp
the measurement). Mixed precision: bfloat16 compute with float32
master params — the MXU-native configuration.

`BASELINE.json.published` is empty — no reference number exists, so
``vs_baseline`` is reported as 1.0 until a reference measurement lands
(BASELINE.md measurement protocol step 4).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.zoo import ResNet50

    on_tpu = jax.devices()[0].platform == "tpu"
    batch = 256 if on_tpu else 8     # 256 ≈ +15% over 128 on v5e
    hw = 224 if on_tpu else 64

    net = ResNet50(num_classes=1000, height=hw, width=hw,
                   compute_dtype="bfloat16").init()

    rng = np.random.RandomState(0)
    x = rng.randn(batch, hw, hw, 3).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, batch)]
    # device-resident batch: measure the train step, not the tunnel
    ds = DataSet(jax.device_put(jnp.asarray(x)),
                 jax.device_put(jnp.asarray(y)))

    steps = 60 if on_tpu else 3
    # fit_steps: `steps` iterations per dispatch (steps_per_execution),
    # removing the per-step host dispatch gap (~+13% at this shape)
    net.fit_steps(ds, steps)     # warmup (compile)
    jax.block_until_ready(net.params)
    float(net.score())

    best = 0.0
    for _trial in range(3):
        t0 = time.perf_counter()
        net.fit_steps(ds, steps)
        jax.block_until_ready(net.params)
        # score() syncs on the final step's loss — guarantees the whole
        # dispatch chain actually executed before we stop the clock
        assert np.isfinite(float(net.score()))
        dt = time.perf_counter() - t0
        best = max(best, steps * batch / dt)

    ips = best
    line = {
        "metric": "resnet50_train_throughput"
                  + ("" if on_tpu else f"_cpu_proxy_{hw}px"),
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": 1.0,
    }
    # Roofline evidence (BENCH_notes_r02.md): XLA cost analysis of the
    # optimized train step (shared helper; flops are a floor).
    try:
        from benchmarks.cost_util import (V5E_BF16_PEAK_TFLOPS,
                                          V5E_HBM_GBPS, graph_step_cost)
        flops, byts = graph_step_cost(net, x, y)
        step_s = batch / ips
        tf = flops / step_s / 1e12
        gbps = byts / step_s / 1e9
        line["tflops"] = round(tf, 1)
        if on_tpu:
            line["pct_bf16_peak"] = round(
                100 * tf / V5E_BF16_PEAK_TFLOPS, 1)
            line["pct_hbm_peak"] = round(100 * gbps / V5E_HBM_GBPS, 1)
    except Exception as e:
        import sys
        print(f"roofline block failed: {e!r}", file=sys.stderr)
    print(json.dumps(line))


if __name__ == "__main__":
    main()
