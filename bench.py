"""Benchmark: ResNet-50 training throughput, images/sec/chip.

BASELINE.md metric #2 (single-chip leg of the north star). Synthetic
ImageNet-shaped data (the metric is compute throughput; input pipeline
is benchmarked separately). `BASELINE.json.published` is empty — no
reference number exists, so ``vs_baseline`` is reported as 1.0 until a
reference measurement lands (BASELINE.md measurement protocol step 4).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.models.zoo import ResNet50

    on_tpu = jax.devices()[0].platform == "tpu"
    batch = 64 if on_tpu else 8
    hw = 224 if on_tpu else 64

    net = ResNet50(num_classes=1000, height=hw, width=hw).init()
    if net._train_step is None:
        net._build_train_step()

    rng = np.random.RandomState(0)
    x = rng.randn(batch, hw, hw, 3).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, batch)]
    ds = DataSet(x, y)

    # warmup (compile)
    for _ in range(3):
        net.fit(ds)
    jax.block_until_ready(net.params)

    steps = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(steps):
        net.fit(ds)
    jax.block_until_ready(net.params)
    dt = time.perf_counter() - t0

    ips = steps * batch / dt
    print(json.dumps({
        "metric": "resnet50_train_throughput"
                  + ("" if on_tpu else f"_cpu_proxy_{hw}px"),
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    main()
